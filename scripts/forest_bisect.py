"""Interleaved A/B: forest-at-once kernel vs the per-depth-gather oracle.

Measures what ISSUE 16 fused — per dispatch, the retained oracle
(``ops/predict.predict_raw_impl``: one gather round per routing depth
over the whole batch) against ONE pallas_call holding a (row-tile x
trees) traversal front in VMEM (``ops/forest.forest_predict_impl``) —
under measurement discipline v2 (PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (the input rows roll by one each link), so the tunnel cannot
  deduplicate bit-identical re-executions;
- every wall ends in a forced 1-element device_get;
- per-dispatch time = (t_K - t_1) / (K - 1), best-of-R, which cancels
  the dispatch + sync overhead shared by both chain lengths.

Parity is asserted before any timing: the two arms must agree on every
row (byte-identical under the CPU interpreter — the tested contract —
and allclose(1e-6) on real Mosaic, whose ulp behavior this script
exists to measure).

This is the validation gate for the ``tpu_forest_kernel`` auto knob:
auto stays "off" until a TPU session runs this script, confirms the
Mosaic lowering and a wall win, and flips the knob (or lets the run
ledger carry the measured answer forward).

On a TPU backend the kernel runs natively; elsewhere it is skipped
unless LGBTPU_PALLAS_INTERPRET=1 (interpreter numbers are
correctness-only — never quote them as perf).

Usage: python scripts/forest_bisect.py [n_rows] [num_feat] [trees]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops.forest import forest_predict_impl
from lightgbm_tpu.ops.predict import predict_raw_impl

REPS = 5
K = 4
LEAVES = 63


def build(n_rows, f, trees, seed=0):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve.session import PredictSession

    rng = np.random.RandomState(seed)
    # grid-quantized features (f32-exact values incl. bin midpoints) so
    # the byte-parity contract is testable off-TPU
    X = np.round(rng.randn(20000, f) * 16) / 64.0
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": LEAVES,
                     "verbosity": -1, "tpu_iter_block": 10},
                    lgb.Dataset(X, label=y), num_boost_round=trees)
    sess = PredictSession(bst, buckets=(n_rows,), forest="on")
    ent = sess._ensure_forest()
    if ent is None:
        raise SystemExit("model is ineligible for the forest kernel "
                         "(see the forest_ineligible telemetry record)")
    fp, f_cat, f_lin = ent
    Xq = np.round(rng.randn(n_rows, f) * 16) / 64.0
    bins, Xr = sess._bin_rows(np.ascontiguousarray(Xq, np.float32))
    pack, has_cat, has_linear = sess._ensure_pack()
    return (bst, fp, f_cat, f_lin, jnp.asarray(bins), jnp.asarray(Xr),
            pack, has_cat, has_linear,
            jnp.asarray(np.ascontiguousarray(Xq, np.float32)))


def make_oracle(X, pack, num_class, has_cat, has_linear):
    """B: the retained per-depth-gather oracle (the serve default)."""
    def make(k):
        @jax.jit
        def run(X, pack):
            def body(carry, _):
                x, acc = carry
                s = predict_raw_impl(x, pack, num_class=num_class,
                                     has_cat=has_cat,
                                     has_linear=has_linear)
                return (jnp.roll(x, 1, axis=0), acc + jnp.sum(s)), None
            (x, acc), _ = jax.lax.scan(
                body, (X, jnp.float32(0)), None, length=k)
            return x.reshape(-1)[:1], acc
        return lambda: run(X, pack)
    return make


def make_forest(bins, Xr, fp, num_class, f_cat, f_lin):
    """A: the fused op — the whole ensemble per row tile in one launch."""
    def make(k):
        @jax.jit
        def run(bins, Xr, fp):
            def body(carry, _):
                b, x, acc = carry
                s = forest_predict_impl(b, x, fp, num_class=num_class,
                                        has_cat=f_cat, has_linear=f_lin)
                return (jnp.roll(b, 1, axis=0), jnp.roll(x, 1, axis=0),
                        acc + jnp.sum(s)), None
            (b, x, acc), _ = jax.lax.scan(
                body, (bins, Xr, jnp.float32(0)), None, length=k)
            return b.reshape(-1)[:1], acc
        return lambda: run(bins, Xr, fp)
    return make


def main(n_rows, f, trees):
    backend = jax.default_backend()
    interp = os.environ.get("LGBTPU_PALLAS_INTERPRET") == "1"
    if backend not in ("tpu", "axon") and not interp:
        print(f"backend={backend}: no Mosaic and LGBTPU_PALLAS_INTERPRET "
              "unset — nothing to bisect (the forest arm needs the "
              "pallas kernel). Exiting.")
        return
    (bst, fp, f_cat, f_lin, bins, Xr, pack, has_cat, has_linear,
     Xq) = build(n_rows, f, trees)
    K_cls = max(1, int(bst.inner.num_tree_per_iteration))
    print(f"backend={backend} n={n_rows} F={f} trees={trees} "
          f"leaves={LEAVES} rounds={int(fp.slot.shape[0])} "
          f"tpad={int(fp.slot.shape[1])}"
          + (" [INTERPRET — correctness only, not perf]"
             if backend not in ("tpu", "axon") else ""))

    # parity before any timing: a fast wrong answer is not a result
    a = np.asarray(forest_predict_impl(bins, Xr, fp, num_class=K_cls,
                                       has_cat=f_cat, has_linear=f_lin))
    b = np.asarray(predict_raw_impl(Xq, pack, num_class=K_cls,
                                    has_cat=has_cat,
                                    has_linear=has_linear))
    byte_equal = a.tobytes() == b.tobytes()
    max_err = float(np.max(np.abs(a - b))) if a.size else 0.0
    print(f"parity: byte_equal={byte_equal} max_abs_err={max_err:.3e}")
    if backend not in ("tpu", "axon") and not byte_equal:
        raise SystemExit("interpret-mode byte parity FAILED — the kernel "
                         "broke its oracle contract; do not time this")
    if not np.allclose(a, b, rtol=0, atol=1e-6):
        raise SystemExit("parity FAILED (max_abs_err %.3e) — fix before "
                         "timing" % max_err)

    res = obs.ab_interleaved(
        [("forest/oracle_gather",
          make_oracle(Xq, pack, K_cls, has_cat, has_linear)),
         ("forest/one_kernel",
          make_forest(bins, Xr, fp, K_cls, f_cat, f_lin))],
        reps=REPS, k=K)
    print()
    for name, per in res.items():
        print(f"{name:24s} {per * 1e3:8.3f} ms/dispatch  "
              f"({n_rows / per / 1e6:7.2f} M rows/s)")
    base = res.get("forest/oracle_gather")
    one = res.get("forest/one_kernel")
    if base and one:
        verdict = ("WIN — flip tpu_forest_kernel auto to on"
                   if base / one > 1.02 and byte_equal
                   else "NO WIN — keep auto=off")
        if base / one > 1.02 and not byte_equal:
            verdict = ("faster but NOT byte-identical on this backend — "
                       "decide whether ulp drift is acceptable before "
                       "flipping auto")
        print(f"\nforest-kernel speedup: {base / one:.2f}x ({verdict})")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    t = int(sys.argv[3]) if len(sys.argv) > 3 else 120
    main(n, f, t)
