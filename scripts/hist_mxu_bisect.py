"""Interleaved A/B: int8 one-hot MXU histogram kernel vs segment einsum.

Measures what ISSUE 17 landed — per smaller-child histogram, the
gather/one-hot einsum oracle (ops/histogram.py hist16_segment /
hist16_segment_q) against the Pallas kernel that builds per-chunk
one-hot matrices in VMEM and contracts them on the MXU
(ops/histogram.py hist_mxu_segment: int8 x int8 -> i32 accumulation on
the quantized path, bf16 hi/lo-16 splits with f32 accumulation on the
float path) — under measurement discipline v2 (PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (a rotating segment start), so the tunnel cannot deduplicate
  bit-identical re-executions;
- every wall ends in a forced 1-element device_get;
- per-pass time = (t_K - t_1) / (K - 1), best-of-R, which cancels the
  dispatch + sync overhead shared by both chain lengths;
- a bitwise gate runs FIRST: kernel vs oracle histograms must be
  byte-identical (f32) / integer-identical (int8) before any timing.

This is the validation gate for the tpu_hist_mxu auto knob: auto stays
"off" until a v5e session runs this script, confirms the Mosaic
lowering of the one-hot dot_general plus a wall win, and flips the
knob (or lets the run ledger carry the measured answer forward).

On a TPU backend the kernel runs natively; elsewhere it is skipped
unless LGBTPU_PALLAS_INTERPRET=1 (interpreter numbers are
correctness-only — never quote them as perf).

Usage: python scripts/hist_mxu_bisect.py [n_rows] [num_feat] [train_rows]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import (hist16_segment, hist16_segment_q,
                                        hist_mxu_segment)

CH = 2048        # histogram chunk (DMA window; must be a multiple of 32)
NUM_BIN = 64
REPS = 5
K = 4


def build_rows(n, f, quantized, seed=0):
    rng = np.random.RandomState(seed)
    guard, width = P.work_spec(f, quantized, "pallas", CH, CH, layout="rows")
    bins = jnp.asarray(rng.randint(0, NUM_BIN, (n, f)).astype(np.uint8))
    ghc = rng.randn(n, 3).astype(np.float32)
    ghc[:, 1] = np.abs(ghc[:, 1])
    ghc[:, 2] = 1.0
    ghc = jnp.asarray(ghc)
    pad = ((guard, guard), (0, 0))
    gscale = hscale = None
    if quantized:
        gscale = jnp.float32(127.0) / (jnp.max(jnp.abs(ghc[:, 0])) + 1e-12)
        hscale = jnp.float32(127.0) / (jnp.max(jnp.abs(ghc[:, 1])) + 1e-12)
        w0 = P.pack_rows_quantized(jnp.pad(bins, pad), jnp.pad(ghc, pad),
                                   jax.random.PRNGKey(seed), gscale, hscale)
    else:
        w0 = P.pack_rows(jnp.pad(bins, pad), jnp.pad(ghc, pad))
    if w0.shape[1] < width:
        w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
    work = jnp.stack([w0, jnp.zeros_like(w0)])
    return work, guard, gscale, hscale


def bitwise_gate(work, guard, n, f, gscale, hscale, quantized):
    """Kernel output must equal the einsum oracle exactly before timing."""
    a, c = jnp.int32(guard + 32), jnp.int32(n - 64)
    if quantized:
        ho = hist16_segment_q(work, jnp.int32(0), a, c, gscale, hscale,
                              num_bins=NUM_BIN, num_feat=f, chunk=CH)
        hk, _ = hist_mxu_segment(work, jnp.int32(0), a, c, num_bins=NUM_BIN,
                                 num_feat=f, quantized=True, gscale=gscale,
                                 hscale=hscale, chunk=CH)
    else:
        ho = hist16_segment(work, jnp.int32(0), a, c, num_bins=NUM_BIN,
                            num_feat=f, chunk=CH)
        hk, _ = hist_mxu_segment(work, jnp.int32(0), a, c, num_bins=NUM_BIN,
                                 num_feat=f, chunk=CH)
    same = bool(jnp.all(ho == hk))
    print("bitwise gate (%s): %s" % ("int8" if quantized else "f32",
                                     "IDENTICAL" if same else "DIVERGED"))
    return same


def make_arm(fn, work, guard, n, f, **kw):
    def make(k):
        @jax.jit
        def run(w):
            def body(carry, _):
                s, acc = carry
                h = fn(w, jnp.int32(0), jnp.int32(guard) + s,
                       jnp.int32(n - 64), num_bins=NUM_BIN, num_feat=f,
                       chunk=CH, **kw)
                if isinstance(h, tuple):
                    h = h[0]
                return ((s + 1) % 32, acc + h[0, 0, 0]), None
            (_, acc), _ = jax.lax.scan(
                body, (jnp.int32(0), jnp.float32(0)), None, length=k)
            return acc.reshape(1), acc
        return lambda: run(work)
    return make


def train_wall(mxu, n, f, iters=10, seed=3):
    """Wall of one warm `lgb.train` with the knob forced on/off (rows
    layout + pallas partition, the kernel's eligibility envelope)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": NUM_BIN,
              "verbosity": -1, "tpu_iter_block": 5,
              "tpu_work_layout": "rows", "tpu_partition_kernel": "pallas",
              "tpu_hist_mxu": mxu}
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=5)        # warmup/compile
    def run():
        with obs.wall("bisect/train_hist_mxu_" + mxu, record=False) as w:
            bst = lgb.train(dict(params), ds, num_boost_round=iters)
            obs.sync(bst.inner.train_score.score)   # trusted wall end
        return w.seconds
    return run


def main(n, f, train_n):
    backend = jax.default_backend()
    pallas_ok = backend in ("tpu", "axon") or P._INTERPRET
    if not pallas_ok:
        print(f"backend={backend}: no Mosaic and LGBTPU_PALLAS_INTERPRET "
              "unset — nothing to bisect (the MXU arm needs the pallas "
              "kernel). Exiting.")
        return
    print(f"backend={backend} n={n} F={f} bins={NUM_BIN} chunk={CH}"
          + (" [INTERPRET — correctness only, not perf]"
             if P._INTERPRET and backend not in ("tpu", "axon") else ""))

    for quantized in (False, True):
        work, guard, gscale, hscale = build_rows(n, f, quantized)
        if not bitwise_gate(work, guard, n, f, gscale, hscale, quantized):
            print("REFUSING to time a diverging configuration.")
            return
        tag = "int8" if quantized else "f32"
        if quantized:
            arms = [(f"hist/{tag}_einsum",
                     make_arm(hist16_segment_q, work, guard, n, f,
                              gscale=gscale, hscale=hscale)),
                    (f"hist/{tag}_mxu",
                     make_arm(hist_mxu_segment, work, guard, n, f,
                              quantized=True, gscale=gscale,
                              hscale=hscale))]
        else:
            arms = [(f"hist/{tag}_einsum",
                     make_arm(hist16_segment, work, guard, n, f)),
                    (f"hist/{tag}_mxu",
                     make_arm(hist_mxu_segment, work, guard, n, f))]
        res = obs.ab_interleaved(arms, reps=REPS, k=K)
        print()
        for name, per in res.items():
            print(f"{name:24s} {per * 1e3:8.3f} ms/pass  "
                  f"({n / per / 1e6:7.1f} M rows/s)")
        base = res.get(f"hist/{tag}_einsum")
        mxu = res.get(f"hist/{tag}_mxu")
        if base and mxu:
            verdict = ("WIN — flip tpu_hist_mxu auto to on"
                       if base / mxu > 1.02 else "NO WIN — keep auto=off")
            print(f"\n{tag} MXU speedup: {base / mxu:.2f}x ({verdict})\n")

    if train_n > 0:
        runs = [("train/off", train_wall("off", train_n, f)),
                ("train/on", train_wall("on", train_n, f))]
        best = {name: np.inf for name, _ in runs}
        for _ in range(3):
            for name, run in runs:           # A, B, A, B per rep
                best[name] = min(best[name], run())
        print()
        for name, w in best.items():
            print(f"{name:24s} {w:8.3f} s  (10 iters, n={train_n})")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    train_n = int(sys.argv[3]) if len(sys.argv) > 3 else 300_000
    main(n, f, train_n)
