"""Serving benchmark CLI: session+batcher vs naive per-request predict.

Usage:
    python scripts/serve_bench.py --quick       # CPU-sized run, ~seconds
    python scripts/serve_bench.py               # full-sized run
    python scripts/serve_bench.py --no-assert   # report without the >=5x gate

Prints ONE JSON line (bench.py style): open-loop rows/s as the headline
metric, vs_baseline = speedup over the naive loop, closed-loop
p50/p90/p99/p999 latency derived from log-bucketed histogram counts
(the buckets themselves ride along in the JSON), the in-run parity
error, and the serve/* telemetry counters. ``--trace PATH`` records the
serve span chain and writes a Perfetto-loadable Chrome trace. Exits
non-zero when the speedup gate fails (parity is always asserted).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--quick", action="store_true",
                    help="small CPU-friendly workload (CI / laptops)")
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--trees", type=int, default=None)
    ap.add_argument("--leaves", type=int, default=None)
    ap.add_argument("--features", type=int, default=None)
    ap.add_argument("--max-batch-rows", type=int, default=8192)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--dispatch-mode", default="continuous",
                    choices=("continuous", "coalesce"),
                    help="batcher discipline: continuous (standing "
                         "dispatch loop) or coalesce (company wait)")
    ap.add_argument("--binned", action="store_true",
                    help="also time the pre-binned predict_binned fast "
                         "path over a constructed Dataset (parity "
                         "asserted in-run)")
    ap.add_argument("--no-assert", action="store_true",
                    help="report the speedup without gating on >=5x")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="record serve spans; write Chrome trace-event "
                         "JSON (Perfetto-loadable) to PATH")
    args = ap.parse_args(argv)

    if args.quick:
        preset = dict(requests=96, trees=30, num_leaves=15, n_features=12,
                      train_rows=4000, closed_loop_requests=48)
    else:
        preset = dict(requests=512, trees=120, num_leaves=63, n_features=28,
                      train_rows=20000, closed_loop_requests=128)
    if args.requests is not None:
        preset["requests"] = args.requests
    if args.trees is not None:
        preset["trees"] = args.trees
    if args.leaves is not None:
        preset["num_leaves"] = args.leaves
    if args.features is not None:
        preset["n_features"] = args.features

    from lightgbm_tpu.serve.bench import run_serve_bench
    if args.trace:
        from lightgbm_tpu.obs_trace import tracer
        tracer.configure("serve_only")
    try:
        result = run_serve_bench(
            rows_per_request=args.rows_per_request,
            max_batch_rows=args.max_batch_rows,
            max_wait_ms=args.max_wait_ms,
            dispatch_mode=args.dispatch_mode,
            binned=args.binned,
            assert_speedup=None if args.no_assert else 5.0,
            **preset)
    except AssertionError as exc:
        print(json.dumps({"error": str(exc)}))
        return 1
    if args.trace:
        result["trace_path"] = args.trace
        result["trace_events"] = tracer.dump(args.trace)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
