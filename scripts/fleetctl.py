"""Fleet status plane CLI: any number of endpoints, the whole fleet.

Usage:
    python scripts/fleetctl.py status <url> [--url <url2> ...]
    python scripts/fleetctl.py lag    <url> [--url <url2> ...]
    python scripts/fleetctl.py tail   <url> [-n 10]   # publishes

``status`` renders ``GET /fleet/status``: store head version + lease
state, then one row per node (trainer, standbys, replicas — local nodes
heartbeat straight into the store, remote replicas POST theirs to
``/fleet/heartbeat``) with role, model version, version skew vs head,
publish->adopt lag (last/p50/p99 ms) and heartbeat age. With MULTIPLE
``--url`` endpoints (a multi-homed region) the per-endpoint documents
are merged into ONE table: nodes are deduplicated by node id and the
newest heartbeat wins, skew is recomputed against the merged head
version, and an ENDPOINTS line reports who answered. ``lag`` is the
convergence columns alone; ``tail`` renders the newest publish events
from ``GET /fleet/publishes`` (first reachable endpoint).

Stdlib-only on purpose: a laptop with no jax can point it at any
trainer. Exit 1 when every endpoint is unreachable or fleet mode is
off.
"""
import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch_json(url, path, timeout_s=5.0):
    """GET <url><path> -> parsed JSON (raises URLError/HTTPError)."""
    req = urllib.request.Request(url.rstrip("/") + path)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_status(url, timeout_s=5.0):
    return fetch_json(url, "/fleet/status", timeout_s)


def merge_status(docs):
    """Merge per-endpoint ``/fleet/status`` documents into one fleet
    view: nodes deduplicated by node id with the NEWEST heartbeat
    winning (two endpoints sharing a store both report every node; after
    a partition heals, one of them may hold a stale copy), head version
    = max across endpoints, lease/log taken from the endpoint that saw
    that head (the most caught-up vantage), and every node's skew
    recomputed against the merged head so the table is self-consistent.
    """
    docs = [d for d in docs if isinstance(d, dict)]
    if not docs:
        return {"nodes": []}
    best = max(docs, key=lambda d: int(d.get("head_version", 0) or 0))
    head = int(best.get("head_version", 0) or 0)
    nodes = {}
    for doc in docs:
        for node in doc.get("nodes", []):
            if not isinstance(node, dict):
                continue
            nid = str(node.get("node", "?"))
            cur = nodes.get(nid)
            if cur is None or float(node.get("ts", 0.0) or 0.0) \
                    > float(cur.get("ts", 0.0) or 0.0):
                nodes[nid] = node
    merged = []
    for nid in sorted(nodes):
        node = dict(nodes[nid])
        node["skew"] = max(0, head - int(node.get("version", 0) or 0))
        merged.append(node)
    return {
        "model_id": best.get("model_id", "?"),
        "head_version": head,
        "lease": best.get("lease") or {},
        "log_bytes": best.get("log_bytes", "?"),
        "compactions": best.get("compactions", "?"),
        "nodes": merged,
    }


def _ms(v):
    return "-" if v is None else "%.1f" % float(v)


def _lag_cell(node):
    lag = node.get("lag_ms") or {}
    if not isinstance(lag, dict) or lag.get("last") is None:
        return "-"
    return "%s/%s/%s" % (_ms(lag.get("last")), _ms(lag.get("p50")),
                         _ms(lag.get("p99")))


def _node_rows(doc):
    rows = []
    for node in doc.get("nodes", []):
        rows.append((
            str(node.get("node", "?")),
            str(node.get("role", "?")),
            str(node.get("version", "?")),
            str(node.get("skew", "?")),
            _lag_cell(node),
            str(node.get("consec_poll_errors",
                         node.get("poll_errors", 0))),
            "%.1f" % float(node.get("poll_backoff_s", 0.0) or 0.0),
            "%.1f" % float(node.get("age_s", 0.0) or 0.0),
        ))
    return rows


def _render_nodes(doc):
    header = ("NODE", "ROLE", "VER", "SKEW", "LAG ms(last/p50/p99)",
              "ERRS", "BACKOFF s", "AGE s")
    rows = _node_rows(doc)
    if not rows:
        return ["(no heartbeats yet — set fleet_heartbeat_interval_s>0 "
                "on every node)"]
    widths = [max(len(header[i]), max(len(r[i]) for r in rows))
              for i in range(len(header))]
    fmt = "  ".join("%%-%ds" % w for w in widths)
    return [fmt % header] + [fmt % r for r in rows]


def _endpoints_line(reachable, unreachable):
    if not unreachable and len(reachable) <= 1:
        return []
    parts = ["%s ok" % u for u in reachable]
    parts += ["%s DOWN" % u for u in unreachable]
    return ["endpoints: " + "  ".join(parts)]


def render_status(doc, reachable=(), unreachable=()):
    """Merged ``/fleet/status`` document -> printable lines."""
    lease = doc.get("lease") or {}
    lines = [
        "model %s  head v%s  log %s B  compactions %s"
        % (doc.get("model_id", "?"), doc.get("head_version", "?"),
           doc.get("log_bytes", "?"), doc.get("compactions", "?")),
        "lease %s"
        % ("held by %s (epoch %s)%s"
           % (lease.get("holder"), lease.get("epoch"),
              " @ %s" % lease["url"] if lease.get("url") else "")
           if lease.get("held") else "free"),
    ]
    lines += _endpoints_line(list(reachable), list(unreachable))
    return lines + _render_nodes(doc)


def render_lag(doc, reachable=(), unreachable=()):
    """Convergence-only view: skew + publish->adopt lag per node."""
    return (["head v%s" % doc.get("head_version", "?")]
            + _endpoints_line(list(reachable), list(unreachable))
            + _render_nodes(doc))


def render_tail(doc, n=10):
    """``/fleet/publishes`` document -> the newest n publish lines."""
    pubs = (doc.get("publishes") or [])[-int(n):]
    if not pubs:
        return ["(nothing published yet)"]
    lines = []
    for e in pubs:
        ts = float(e.get("ts", 0.0) or 0.0)
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ts)) if ts else "?"
        lines.append("v%-6s %-19s %-10s epoch=%s"
                     % (e.get("version", "?"), when,
                        e.get("event", "?"), e.get("lease_epoch", 0)))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fleetctl", description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("status", "lag", "tail"))
    ap.add_argument("url", nargs="?",
                    help="fleet base url, e.g. http://host:8080")
    ap.add_argument("--url", dest="urls", action="append", default=[],
                    metavar="URL",
                    help="additional fleet endpoint (repeatable; "
                    "status/lag merge all endpoints into one table)")
    ap.add_argument("-n", type=int, default=10,
                    help="tail: newest N publishes (default 10)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    urls = ([args.url] if args.url else []) + list(args.urls)
    # dedup, order-preserving: `fleetctl status URL --url URL` is one
    # endpoint, not the same document merged with itself
    seen = set()
    urls = [u for u in urls
            if u.rstrip("/") not in seen
            and not seen.add(u.rstrip("/"))]
    if not urls:
        ap.error("need at least one endpoint (positional url or --url)")
    if args.command == "tail":
        last_exc = None
        for url in urls:
            try:
                doc = fetch_json(url, "/fleet/publishes", args.timeout)
            except (urllib.error.URLError, OSError, ValueError) as exc:
                last_exc = (url, exc)
                continue
            for line in render_tail(doc, args.n):
                print(line)
            return 0
        print("fleetctl: cannot reach %s: %s" % last_exc,
              file=sys.stderr)
        return 1
    docs, reachable, unreachable = [], [], []
    for url in urls:
        try:
            docs.append(fetch_status(url, args.timeout))
            reachable.append(url)
        except urllib.error.HTTPError as exc:
            print("fleetctl: %s answered %d (fleet store attached?)"
                  % (url, exc.code), file=sys.stderr)
            unreachable.append(url)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print("fleetctl: cannot reach %s: %s" % (url, exc),
                  file=sys.stderr)
            unreachable.append(url)
    if not docs:
        return 1
    doc = merge_status(docs)
    render = render_status if args.command == "status" else render_lag
    for line in render(doc, reachable, unreachable):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
