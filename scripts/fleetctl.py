"""Fleet status plane CLI: one trainer endpoint, the whole fleet.

Usage:
    python scripts/fleetctl.py status <trainer-url>   # per-node rollup
    python scripts/fleetctl.py lag    <trainer-url>   # convergence lag
    python scripts/fleetctl.py tail   <trainer-url> [-n 10]  # publishes

``status`` renders ``GET /fleet/status``: store head version + lease
state, then one row per node (trainer, standbys, replicas — local nodes
heartbeat straight into the store, remote replicas POST theirs to
``/fleet/heartbeat``) with role, model version, version skew vs head,
publish->adopt lag (last/p50/p99 ms) and heartbeat age. ``lag`` is the
convergence columns alone; ``tail`` renders the newest publish events
from ``GET /fleet/publishes``.

Stdlib-only on purpose: a laptop with no jax can point it at any
trainer. Exit 1 when the endpoint is unreachable or fleet mode is off.
"""
import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch_json(url, path, timeout_s=5.0):
    """GET <url><path> -> parsed JSON (raises URLError/HTTPError)."""
    req = urllib.request.Request(url.rstrip("/") + path)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_status(url, timeout_s=5.0):
    return fetch_json(url, "/fleet/status", timeout_s)


def _ms(v):
    return "-" if v is None else "%.1f" % float(v)


def _lag_cell(node):
    lag = node.get("lag_ms") or {}
    if not isinstance(lag, dict) or lag.get("last") is None:
        return "-"
    return "%s/%s/%s" % (_ms(lag.get("last")), _ms(lag.get("p50")),
                         _ms(lag.get("p99")))


def _node_rows(doc):
    rows = []
    for node in doc.get("nodes", []):
        rows.append((
            str(node.get("node", "?")),
            str(node.get("role", "?")),
            str(node.get("version", "?")),
            str(node.get("skew", "?")),
            _lag_cell(node),
            str(node.get("consec_poll_errors",
                         node.get("poll_errors", 0))),
            "%.1f" % float(node.get("poll_backoff_s", 0.0) or 0.0),
            "%.1f" % float(node.get("age_s", 0.0) or 0.0),
        ))
    return rows


def _render_nodes(doc):
    header = ("NODE", "ROLE", "VER", "SKEW", "LAG ms(last/p50/p99)",
              "ERRS", "BACKOFF s", "AGE s")
    rows = _node_rows(doc)
    if not rows:
        return ["(no heartbeats yet — set fleet_heartbeat_interval_s>0 "
                "on every node)"]
    widths = [max(len(header[i]), max(len(r[i]) for r in rows))
              for i in range(len(header))]
    fmt = "  ".join("%%-%ds" % w for w in widths)
    return [fmt % header] + [fmt % r for r in rows]


def render_status(doc):
    """``/fleet/status`` document -> printable lines."""
    lease = doc.get("lease") or {}
    lines = [
        "model %s  head v%s  log %s B  compactions %s"
        % (doc.get("model_id", "?"), doc.get("head_version", "?"),
           doc.get("log_bytes", "?"), doc.get("compactions", "?")),
        "lease %s"
        % ("held by %s (epoch %s)" % (lease.get("holder"),
                                      lease.get("epoch"))
           if lease.get("held") else "free"),
    ]
    return lines + _render_nodes(doc)


def render_lag(doc):
    """Convergence-only view: skew + publish->adopt lag per node."""
    return ["head v%s" % doc.get("head_version", "?")] + _render_nodes(doc)


def render_tail(doc, n=10):
    """``/fleet/publishes`` document -> the newest n publish lines."""
    pubs = (doc.get("publishes") or [])[-int(n):]
    if not pubs:
        return ["(nothing published yet)"]
    lines = []
    for e in pubs:
        ts = float(e.get("ts", 0.0) or 0.0)
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ts)) if ts else "?"
        lines.append("v%-6s %-19s %-10s epoch=%s"
                     % (e.get("version", "?"), when,
                        e.get("event", "?"), e.get("lease_epoch", 0)))
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fleetctl", description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("status", "lag", "tail"))
    ap.add_argument("url", help="trainer base url, e.g. http://host:8080")
    ap.add_argument("-n", type=int, default=10,
                    help="tail: newest N publishes (default 10)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    try:
        if args.command == "tail":
            doc = fetch_json(args.url, "/fleet/publishes", args.timeout)
            lines = render_tail(doc, args.n)
        else:
            doc = fetch_status(args.url, args.timeout)
            lines = (render_status if args.command == "status"
                     else render_lag)(doc)
    except urllib.error.HTTPError as exc:
        print("fleetctl: %s answered %d (fleet store attached?)"
              % (args.url, exc.code), file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError, ValueError) as exc:
        print("fleetctl: cannot reach %s: %s" % (args.url, exc),
              file=sys.stderr)
        return 1
    for line in lines:
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
