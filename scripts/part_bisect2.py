"""Bisect the REAL partition kernel's per-call fixed cost (post table fix).

Variants strip stages (results wrong for stripped ones — timing only).
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

import lightgbm_tpu.ops.partition as P

ALIGN = P.ALIGN
N = 1 << 20
CH = 1024
SB = 256
REPS = 254
W = 128

work = jnp.zeros((2, N + 4 * CH, W), jnp.uint8)


def make_kernel(ch, sb, width, *, do_prefill, do_chunks, do_sub, do_flush,
                do_drain, do_rmw):
    f32 = jnp.float32
    lcap = 2 * ch
    nsub = ch // sb

    def kern(sref, work_in, work_ref, lt_ref, tril, cin, pre, lstage, rstage,
             lfb, rfb, sem):
        src_plane = sref[0]
        start = sref[1]
        cnt = sref[2]
        feat = sref[3]
        dst_plane = 1 - src_plane

        def a32(x):
            return (x // ALIGN) * ALIGN

        lbase0 = (start // ALIGN) * ALIGN
        head_l = start - lbase0
        end = start + cnt
        rtop = ((end - 1) // ALIGN) * ALIGN
        rbase0 = rtop + ALIGN
        tail_r = rbase0 - end
        astart = lbase0
        head = head_l
        tot = head + cnt
        nchunks = (tot + ch - 1) // ch

        row_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 0)
        col_i = jax.lax.broadcasted_iota(jnp.int32, (sb, sb), 1)
        tril[:] = jnp.clip(row_i - col_i, 0, 1).astype(f32) \
            .astype(jnp.bfloat16)
        iota_sb = jax.lax.broadcasted_iota(jnp.int32, (sb, 1), 0)
        lane_w = jax.lax.broadcasted_iota(jnp.int32, (ch, width), 1)
        sub_i = jax.lax.broadcasted_iota(jnp.int32, (ch, 1), 0)

        if do_prefill:
            pl_in = pltpu.make_async_copy(
                work_in.at[dst_plane, pl.ds(lbase0, ALIGN), :], pre.at[0],
                sem.at[2])
            pl_in.start()
            pr_in = pltpu.make_async_copy(
                work_in.at[dst_plane, pl.ds(rtop, ALIGN), :], pre.at[1],
                sem.at[3])
            pr_in.start()

        def start_in(i, slot):
            pltpu.make_async_copy(
                work_in.at[src_plane, pl.ds(a32(astart + i * ch), ch), :],
                cin.at[slot], sem.at[slot]).start()

        start_in(0, 0)
        if do_prefill:
            pl_in.wait()
            lstage[0:ALIGN, :] = pre[0].astype(jnp.int32).astype(f32)
            pr_in.wait()
            rstage[ch - ALIGN:ch, :] = pre[1].astype(jnp.int32).astype(f32)

        def flush(stage, fb, flushed, left, sem_base):
            half = jax.lax.rem(flushed // ch, 2)
            slot = half
            nflush = flushed // ch

            @pl.when(nflush >= 2)
            def _():
                pltpu.make_async_copy(
                    fb.at[slot], work_ref.at[dst_plane, pl.ds(0, ch), :],
                    sem.at[sem_base + slot]).wait()
            hs = (half * ch // 8) * 8
            fb[slot] = stage[pl.ds(hs, ch)].astype(jnp.int32) \
                .astype(jnp.uint8)
            if left:
                at = a32(lbase0 + flushed)
            else:
                at = a32(rbase0 - flushed - ch)
            pltpu.make_async_copy(
                fb.at[slot], work_ref.at[dst_plane, pl.ds(at, ch), :],
                sem.at[sem_base + slot]).start()

        iota_sb8 = jax.lax.broadcasted_iota(jnp.int32, (sb + 8, 1), 0)

        def append(stage, out8, n_, ws, dlt, fill_sel_left):
            ws8 = (ws // 8) * 8
            win = stage[pl.ds(ws8, sb + 8)]
            if fill_sel_left:
                m = (iota_sb8 >= dlt) & (iota_sb8 < dlt + n_)
            else:
                m = (iota_sb8 >= dlt + sb - n_) & (iota_sb8 < dlt + sb)
            stage[pl.ds(ws8, sb + 8)] = jnp.where(m, out8, win)

            @pl.when(ws + sb > lcap)
            def _():
                ov = ws + sb - lcap
                stage[0:sb, :] = jnp.where(iota_sb < ov,
                                           stage[lcap:lcap + sb, :],
                                           stage[0:sb, :])

        def body(i, carry):
            p_l, p_r, fl_l, fl_r = carry
            slot = jax.lax.rem(i, 2)
            pltpu.make_async_copy(
                work_in.at[src_plane, pl.ds(a32(astart + i * ch), ch), :],
                cin.at[slot], sem.at[slot]).wait()

            @pl.when(i + 1 < nchunks)
            def _():
                start_in(i + 1, 1 - slot)

            cf = cin[slot].astype(jnp.int32).astype(f32)
            col = jnp.sum(jnp.where(lane_w == feat, cf, 0.0), axis=1,
                          keepdims=True)
            coli = col.astype(jnp.int32)
            word = jax.lax.shift_right_logical(coli, 5)
            wvals = jnp.zeros((ch, 1), jnp.int32)
            for w in range(P.TABLE_WORDS):
                wvals = jnp.where(word == w, sref[4 + w], wvals)
            bit = jnp.bitwise_and(coli, 31)
            go = jnp.bitwise_and(
                jax.lax.shift_right_logical(wvals, bit), 1) > 0
            pos = sub_i + i * ch
            valid = (pos >= head) & (pos < tot)

            if do_sub:
                for s in range(nsub):
                    sub = cf[s * sb:(s + 1) * sb]
                    gl = go[s * sb:(s + 1) * sb] & valid[s * sb:(s + 1) * sb]
                    gr = (~go[s * sb:(s + 1) * sb]) \
                        & valid[s * sb:(s + 1) * sb]
                    flags = jnp.concatenate(
                        [gl.astype(jnp.bfloat16), gr.astype(jnp.bfloat16)],
                        axis=1)
                    ranks = jax.lax.dot(tril[:], flags,
                                        preferred_element_type=f32)
                    nl = jnp.sum(gl.astype(jnp.int32))
                    nr = jnp.sum(gr.astype(jnp.int32))
                    lrank = ranks[:, 0:1].astype(jnp.int32)
                    rrank = ranks[:, 1:2].astype(jnp.int32)
                    ws_l = jax.lax.rem(p_l, lcap)
                    dlt_l = ws_l - (ws_l // 8) * 8
                    ws_r = jax.lax.rem(
                        ch - jax.lax.rem(p_r, lcap) - sb + 2 * lcap, lcap)
                    dlt_r = ws_r - (ws_r // 8) * 8
                    dest_l = jnp.where(gl, lrank + dlt_l, -1)
                    dest_r = jnp.where(gr, sb - 1 - rrank + dlt_r, -1)
                    j_i = jax.lax.broadcasted_iota(jnp.int32, (sb + 8, sb), 0)
                    perm_l = (1 - jnp.clip(jnp.abs(j_i - dest_l.reshape(1, sb)),
                                           0, 1)).astype(f32) \
                        .astype(jnp.bfloat16)
                    perm_r = (1 - jnp.clip(jnp.abs(j_i - dest_r.reshape(1, sb)),
                                           0, 1)).astype(f32) \
                        .astype(jnp.bfloat16)
                    sub_bf = sub.astype(jnp.bfloat16)
                    out_l = jax.lax.dot(perm_l, sub_bf,
                                        preferred_element_type=f32)
                    out_r = jax.lax.dot(perm_r, sub_bf,
                                        preferred_element_type=f32)
                    append(lstage, out_l, nl, ws_l, dlt_l, True)
                    p_l = p_l + nl
                    if do_flush:
                        @pl.when(p_l - fl_l >= ch)
                        def _():
                            flush(lstage, lfb, fl_l, True, 4)
                        fl_l = jnp.where(p_l - fl_l >= ch, fl_l + ch, fl_l)
                    append(rstage, out_r, nr, ws_r, dlt_r, False)
                    p_r = p_r + nr
                    if do_flush:
                        @pl.when(p_r - fl_r >= ch)
                        def _():
                            flush(rstage, rfb, fl_r, False, 6)
                        fl_r = jnp.where(p_r - fl_r >= ch, fl_r + ch, fl_r)
            return p_l, p_r, fl_l, fl_r

        if do_chunks:
            p_l, p_r, fl_l, fl_r = jax.lax.fori_loop(
                0, nchunks, body, (head_l, tail_r, jnp.int32(0), jnp.int32(0)))
        else:
            p_l, p_r, fl_l, fl_r = (head_l + cnt, tail_r, jnp.int32(0),
                                    jnp.int32(0))

        if do_drain:
            fill_l = p_l - fl_l
            fill_r = p_r - fl_r
            d = fill_l + fill_r
            dstart = lbase0 + fl_l
            for base, fl in ((4, fl_l), (6, fl_r)):
                nf = fl // ch
                for back in (1, 2):
                    @pl.when(nf >= back)
                    def _(base=base, nf=nf, back=back):
                        pltpu.make_async_copy(
                            lfb.at[jax.lax.rem(nf - back, 2)],
                            work_ref.at[dst_plane, pl.ds(0, ch), :],
                            sem.at[base + jax.lax.rem(nf - back, 2)]).wait()

            def read_circ(stage, qstart):
                qs = jax.lax.rem(jax.lax.rem(qstart, lcap) + lcap, lcap)
                qs8 = (qs // 8) * 8
                dlt = qs - qs8
                a = pltpu.roll(stage[pl.ds(qs8, ch + 8)], -dlt, 0)[:ch]
                b = stage[pl.ds(0, ch)]
                lim = lcap - qs
                rolled = pltpu.roll(b, lim, 0)
                return jnp.where(sub_i[:ch] < lim, a, rolled)

            qr0 = jax.lax.rem(ch - jax.lax.rem(p_r, lcap) + 2 * lcap, lcap)

            def drain_tile(o):
                lrows = read_circ(lstage, fl_l + o)
                rrows = read_circ(rstage, qr0 + (o - fill_l))
                off = sub_i[:ch] + o
                return jnp.where(off < fill_l, lrows, rrows)

            nfull = d // ch
            MAXT = 4

            def dbody(t, _):
                @pl.when(t < nfull)
                def _():
                    slot = jax.lax.rem(t, 2)

                    @pl.when(t >= 2)
                    def _():
                        pltpu.make_async_copy(
                            lfb.at[slot],
                            work_ref.at[dst_plane, pl.ds(0, ch), :],
                            sem.at[4 + slot]).wait()
                    lfb[slot] = drain_tile(t * ch).astype(jnp.int32) \
                        .astype(jnp.uint8)
                    pltpu.make_async_copy(
                        lfb.at[slot],
                        work_ref.at[dst_plane,
                                    pl.ds(a32(dstart + t * ch), ch), :],
                        sem.at[4 + slot]).start()
                return 0

            jax.lax.fori_loop(0, MAXT, dbody, 0)
            for back in range(1, 3):
                @pl.when(nfull >= back)
                def _(back=back):
                    pltpu.make_async_copy(
                        lfb.at[jax.lax.rem(nfull - back, 2)],
                        work_ref.at[dst_plane, pl.ds(0, ch), :],
                        sem.at[4 + jax.lax.rem(nfull - back, 2)]).wait()

            rem_ = d - nfull * ch
            if do_rmw:
                @pl.when(rem_ > 0)
                def _():
                    at = a32(dstart + d - ch)
                    rd = pltpu.make_async_copy(
                        work_in.at[dst_plane, pl.ds(at, ch), :], lfb.at[0],
                        sem.at[4])
                    rd.start()
                    rd.wait()
                    tile = drain_tile(d - ch)
                    old = lfb[0].astype(jnp.int32).astype(f32)
                    off = sub_i[:ch] + (d - ch)
                    keep_new = (off >= jnp.int32(nfull) * ch) & (off >= 0)
                    merged = jnp.where(keep_new, tile, old)
                    lfb[0] = merged.astype(jnp.int32).astype(jnp.uint8)
                    wr = pltpu.make_async_copy(
                        lfb.at[0], work_ref.at[dst_plane, pl.ds(at, ch), :],
                        sem.at[4])
                    wr.start()
                    wr.wait()
        else:
            # still must consume the in-flight input DMA semaphores? they
            # were waited in body; nothing outstanding unless flushes ran
            pass
        lt_ref[0] = p_l - head_l

    return kern


def bench(name, **flags):
    kern = make_kernel(CH, SB, W, **flags)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        scratch_shapes=[
            pltpu.VMEM((SB, SB), jnp.bfloat16),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.VMEM((2, ALIGN, W), jnp.uint8),
            pltpu.VMEM((3 * CH, W), jnp.float32),
            pltpu.VMEM((3 * CH, W), jnp.float32),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.SemaphoreType.DMA((8,)),
        ],
    )

    @jax.jit
    def chain(work, cnt):
        def body(i, carry):
            work, tot = carry
            scalars = jnp.concatenate([
                jnp.stack([jax.lax.rem(i, 2), jnp.int32(2 * CH), cnt,
                           jax.lax.rem(i, 28)]),
                jnp.zeros((P.TABLE_WORDS,), jnp.int32)])
            w2, lt = pl.pallas_call(
                kern, name="part_bisect2", grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                           jax.ShapeDtypeStruct((1,), jnp.int32)],
                input_output_aliases={1: 0},
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",),
                    vmem_limit_bytes=100 * 1024 * 1024),
            )(scalars, work)
            return w2, tot + lt[0]
        return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))

    for cnt in (256,):
        obs.sync(chain(work, jnp.int32(cnt)))
        best = 1e9
        for _ in range(2):
            with obs.wall("part_bisect2/stage", record=False) as w:
                obs.sync(chain(work, jnp.int32(cnt)))
            best = min(best, w.seconds)
        print("%-44s cnt=%5d %8.1f us/call" % (name, cnt, best / REPS * 1e6))


full = dict(do_prefill=True, do_chunks=True, do_sub=True, do_flush=True,
            do_drain=True, do_rmw=True)
bench("full", **full)
bench("no rmw", **{**full, "do_rmw": False})
bench("no drain", **{**full, "do_drain": False, "do_rmw": False})
bench("no flush", **{**full, "do_flush": False, "do_drain": False,
                     "do_rmw": False})
bench("no sub", **{**full, "do_sub": False, "do_flush": False,
                   "do_drain": False, "do_rmw": False})
bench("no chunks", **{**full, "do_chunks": False, "do_sub": False,
                      "do_flush": False, "do_drain": False, "do_rmw": False})
bench("no prefill/chunks", do_prefill=False, do_chunks=False, do_sub=False,
      do_flush=False, do_drain=False, do_rmw=False)
