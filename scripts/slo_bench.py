"""Closed-loop serving SLO benchmark over the real HTTP surface.

Usage:
    python scripts/slo_bench.py --quick                # CPU-sized run
    python scripts/slo_bench.py --quick --online       # + live refit loop
    python scripts/slo_bench.py --quick --fleet        # trainer + 2 replicas
    python scripts/slo_bench.py --quick --failover     # lease-crash drill
    python scripts/slo_bench.py --quick --noisy-tenant # fairness demo
    python scripts/slo_bench.py --baseline SLO_BASELINE.json
    python scripts/slo_bench.py --against SLO_BASELINE.json
    python scripts/slo_bench.py --p99-target-ms 50

``--fleet`` runs the PR-11 fleet e2e under closed-loop load: one trainer
publishes promotions through a durable FleetStore while TWO serving
replicas (own boosters, own HTTP servers) watch it and hot-swap; the
gate checks both replicas converge to the published version with exactly
one whole-model version bump per applied publish.

``--failover`` is the lease-crash drill under the same closed-loop load:
an active trainer (short lease ttl) and a warm standby share one store;
after the first promotion the active is killed WITHOUT releasing its
lease. Gates: the standby goes active within the ttl window, the dead
holder's late publish raises StaleLeaseError, a post-takeover promotion
lands, both replicas re-converge, version tokens stay unique, and every
applied publish is exactly one whole-model version bump.

``--failover`` then runs a SECOND drill (two JSON lines total, both must
pass): the region/two-endpoint drill. A trainer behind its own HTTP
endpoint holds the lease; two store-host endpoints expose the same store
over ``/fleet/*`` and forward labeled ``/ingest`` traffic to the lease
holder; a serving replica watches the pair through a
``MultiEndpointStore``. Mid-load the replica's PRIMARY endpoint is
killed. Gates: the watcher fails over to the survivor and re-converges,
publish->adopt lag p99 stays under ``--lag-p99-target-ms``, ZERO acked
ingest rows are dropped on the way through forwarding, every applied
publish is one version bump, and a cold standby boots over HTTP from
snapshot + tail (``cold_boot_s`` reported in the JSON).

``--noisy-tenant`` measures per-tenant fairness: a quota-respecting
tenant's client-side p99 is taken solo, then again while a flooding
tenant saturates its own quota; the gate fails when the polite tenant is
shed at all or pushed past ``--fair-p99-factor`` x its solo p99.

Closed loop: N client threads POST /predict against an in-process
``PredictServer`` on an ephemeral port, each sending its next request
only when the previous one answered — the arrival rate adapts to the
server, so the latency distribution is the service time under sustained
concurrency, not queue blow-up under an arbitrary open-loop rate.

With ``--online`` a labeled-ingestion thread feeds POST /ingest while the
clients run, so the reported p99 INCLUDES background train cycles and
promotion swaps — the number the PERF.md promotion-cost note quotes.

``--ab-dispatch`` is the dispatch-discipline A/B: four interleaved
closed-loop windows (ABBA order: continuous, coalesce, coalesce,
continuous), each with a fresh server and fresh telemetry, so machine
drift cannot masquerade as a dispatch-mode effect. The gate fails
unless continuous dispatch materially reduces pooled queue-wait p99
versus coalesce.

Prints ONE JSON line (bench.py style): p50/p90/p99/p999 from the
``serve/latency_ms`` histogram, the same percentiles from
``serve/queue_wait_ms`` (time from submit until batch seal — the
quantity continuous dispatch shrinks), throughput, shed/error counts,
and the online promotion counters. Gates (exit 1 on miss): ``--p99-target-ms``
absolute, or ``--against BASELINE.json`` relative (p99 within
``--tolerance``x of the recorded baseline). ``--baseline PATH`` records
the run for future ``--against`` gates.
"""
import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _client(base, n, rows, payload, fails, sheds, tenant=None, lat=None):
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Tenant"] = tenant
    for _ in range(n):
        req = Request(base + "/predict", data=payload, headers=headers)
        t0 = time.perf_counter()  # graftlint: disable=naked-timer -- client-side latency clock, measures the server
        try:
            with urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
                if len(out["predictions"]) != rows:
                    fails.append("short response")
                elif lat is not None:
                    lat.append((time.perf_counter() - t0) * 1000.0)  # graftlint: disable=naked-timer -- client-side latency clock
        except HTTPError as exc:
            (sheds if exc.code == 429 else fails).append(exc.code)
        except Exception as exc:  # noqa: BLE001 - benchmark accounting
            fails.append(repr(exc))


def _train_seed(preset):
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    w = rng.randn(preset["features"])
    X = rng.randn(preset["train_rows"], preset["features"])
    y = (X @ w + 0.2 * rng.randn(len(X)) > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": preset["leaves"]},
                    lgb.Dataset(X, label=y),
                    num_boost_round=preset["trees"])
    return bst, rng, w


def _preset(args):
    if args.quick:
        return dict(train_rows=2000, trees=20, leaves=15, features=10,
                    clients=4, requests=240)
    return dict(train_rows=20000, trees=100, leaves=31, features=20,
                clients=8, requests=2000)


def _run_fleet(args) -> int:
    """Trainer + two serving replicas over one durable store, closed-loop
    load on both replicas, convergence + whole-model gates."""
    import tempfile

    from lightgbm_tpu import obs
    from lightgbm_tpu.fleet import FleetStore, ReplicaWatcher, \
        bootstrap_model
    from lightgbm_tpu.online import OnlineTrainer
    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    clients = args.clients or preset["clients"]
    total = args.requests or preset["requests"]
    rows = args.rows_per_request
    bst, rng, w = _train_seed(preset)

    tmp = tempfile.mkdtemp(prefix="lgbtpu_fleet_bench_")
    store = FleetStore(tmp, "default")
    store.publish(bst.model_to_string(), event="boot")

    # the trainer process: ingests labeled traffic, publishes promotions
    trainer = OnlineTrainer(bst, trigger_rows=max(256, rows * 8),
                            min_rows=128, shadow_rows=1024, store=store)
    # two serving replicas, each with a PRIVATE booster bootstrapped from
    # the store and a watcher hot-swapping newer publishes into it
    replicas = []
    for i in range(2):
        rb, applied = bootstrap_model(store)
        server = PredictServer(rb, port=0, buckets=(64, 256), warmup=True,
                               max_wait_ms=2.0)
        server.fleet_watcher = ReplicaWatcher(
            rb, store, poll_interval_s=0.1, applied_version=applied)
        th = threading.Thread(target=server.serve_forever,
                              name="slo-fleet-replica%d" % i, daemon=True)
        th.start()
        host, port = server.address
        replicas.append({"server": server, "thread": th, "booster": rb,
                         "base": "http://%s:%d" % (host, port),
                         "v0": rb.inner.model_version})

    stop_ingest = threading.Event()

    def ingest_loop():
        while not stop_ingest.is_set():
            Xi = rng.randn(64, preset["features"])
            yi = (Xi @ w > 0).astype("float64")
            try:
                trainer.ingest(Xi, yi)
            except Exception:  # noqa: BLE001 - keep feeding
                pass
            time.sleep(0.02)

    ingester = threading.Thread(target=ingest_loop,
                                name="slo-fleet-ingest", daemon=True)
    ingester.start()

    fails, sheds = [], []
    threads = [threading.Thread(
        target=_client, name="slo-fleet-c%d" % i,
        args=(replicas[i % 2]["base"], total // clients, rows,
              json.dumps({"rows": rng.randn(
                  rows, preset["features"]).tolist()}).encode(),
              fails, sheds))
        for i in range(clients)]
    t0 = obs.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = obs.monotonic() - t0

    # grace window: a promotion must land and BOTH replicas converge on it
    deadline = obs.monotonic() + (30 if args.quick else 60)
    converged = False
    while obs.monotonic() < deadline:
        published = store.state()["last_published_version"]
        if trainer.state()["promotions"] >= 1 and all(
                r["server"].fleet_watcher.applied_version == published
                for r in replicas):
            converged = True
            break
        time.sleep(0.1)
    stop_ingest.set()
    ingester.join(timeout=30)
    trainer.close()
    published = store.state()["last_published_version"]

    rep_docs = []
    bumps_ok = True
    for r in replicas:
        st = r["server"].fleet_watcher.state()
        bumps = r["booster"].inner.model_version - r["v0"]
        # whole-model invariant: every applied publish is exactly ONE
        # atomic adopt — version bumps match swap count
        bumps_ok = bumps_ok and bumps == st["swaps"]
        rep_docs.append({"applied_version": st["applied_version"],
                         "swaps": st["swaps"],
                         "version_bumps": bumps})
        r["server"].shutdown()
        r["thread"].join(timeout=30)
        r["server"].close()

    tstate = trainer.state()
    result = {
        "bench": "slo_fleet",
        "quick": bool(args.quick),
        "elapsed_s": round(elapsed, 3),
        "published_version": published,
        "promotions": tstate["promotions"],
        "rejections": tstate["rejections"],
        "replicas": rep_docs,
        "store_dir": tmp,
        "errors": fails[:5],
    }
    gate_msgs = []
    if fails:
        gate_msgs.append("%d request failures" % len(fails))
    if tstate["promotions"] < 1:
        gate_msgs.append("no promotion landed within the grace window")
    if not converged:
        gate_msgs.append("replicas did not converge to v%d" % published)
    if not bumps_ok:
        gate_msgs.append("version bumps != applied swaps (torn swap?)")
    result["pass"] = not gate_msgs
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    print(json.dumps(result))
    return 0 if result["pass"] else 1


def _run_failover(args) -> int:
    """Failover e2e under load: active trainer A (short lease) + standby
    B + two serving replicas; A crashes without releasing its lease, B
    must take over inside the ttl window, keep publishing, and both
    replicas must converge — while A's zombie publish stays fenced."""
    import tempfile

    from lightgbm_tpu import obs
    from lightgbm_tpu.basic import LightGBMError
    from lightgbm_tpu.fleet import FleetStore, ReplicaWatcher, \
        bootstrap_model
    from lightgbm_tpu.fleet.store import StaleLeaseError
    from lightgbm_tpu.online import OnlineTrainer

    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    clients = args.clients or preset["clients"]
    total = args.requests or preset["requests"]
    rows = args.rows_per_request
    # the ttl must outlast a full train cycle under load, or the active
    # trainer's heartbeat (every ttl/3, between cycles) misses and the
    # standby steals the lease before the scripted crash
    ttl = 5.0
    bst, rng, w = _train_seed(preset)

    tmp = tempfile.mkdtemp(prefix="lgbtpu_failover_bench_")
    store_a = FleetStore(tmp, "default")
    store_a.publish(bst.model_to_string(), event="boot")
    online_kw = dict(trigger_rows=max(256, rows * 8), min_rows=128,
                     shadow_rows=1024, lease_ttl_s=ttl)

    trainer_a = OnlineTrainer(bst, store=store_a, holder_id="trainer-a",
                              **online_kw)
    if not trainer_a.wait_for_lease(30):
        print(json.dumps({"bench": "slo_failover", "pass": False,
                          "gate_failures": ["trainer-a never went active"]}))
        return 1
    # the standby runs as a second process would: its own store handle
    # over the same dir, its own booster bootstrapped from the publishes
    store_b = FleetStore(tmp, "default")
    bst_b, _ = bootstrap_model(store_b)
    trainer_b = OnlineTrainer(bst_b, store=store_b, holder_id="trainer-b",
                              **online_kw)

    replicas = []
    for i in range(2):
        rb, applied = bootstrap_model(store_a)
        server = PredictServer(rb, port=0, buckets=(64, 256), warmup=True,
                               max_wait_ms=2.0)
        server.fleet_watcher = ReplicaWatcher(
            rb, store_a, poll_interval_s=0.1, applied_version=applied)
        th = threading.Thread(target=server.serve_forever,
                              name="slo-failover-replica%d" % i,
                              daemon=True)
        th.start()
        host, port = server.address
        replicas.append({"server": server, "thread": th, "booster": rb,
                         "base": "http://%s:%d" % (host, port),
                         "v0": rb.inner.model_version})

    stop_ingest = threading.Event()
    target = {"trainer": trainer_a}

    def ingest_loop():
        while not stop_ingest.is_set():
            Xi = rng.randn(64, preset["features"])
            yi = (Xi @ w > 0).astype("float64")
            try:
                target["trainer"].ingest(Xi, yi)
            except Exception:  # noqa: BLE001 - keep feeding
                pass
            time.sleep(0.02)

    ingester = threading.Thread(target=ingest_loop,
                                name="slo-failover-ingest", daemon=True)
    ingester.start()

    fails, sheds = [], []
    threads = [threading.Thread(
        target=_client, name="slo-failover-c%d" % i,
        args=(replicas[i % 2]["base"], total // clients, rows,
              json.dumps({"rows": rng.randn(
                  rows, preset["features"]).tolist()}).encode(),
              fails, sheds))
        for i in range(clients)]
    for t in threads:
        t.start()

    gate_msgs = []
    grace = 30 if args.quick else 60

    # phase 1: A must land at least one promotion before we kill it
    deadline = obs.monotonic() + grace
    while obs.monotonic() < deadline \
            and trainer_a.state()["promotions"] < 1:
        time.sleep(0.1)
    promos_a = trainer_a.state()["promotions"]
    if promos_a < 1:
        gate_msgs.append("trainer-a landed no promotion in the grace "
                         "window")

    # phase 2: crash A (lease left to expire, fence left armed) and time
    # the standby's takeover
    trainer_a.close(timeout=30, release_lease=False)
    t_crash = obs.monotonic()
    target["trainer"] = trainer_b
    takeover_s = None
    deadline = t_crash + ttl * 10 + grace
    while obs.monotonic() < deadline:
        if trainer_b.state()["role"] == "active":
            takeover_s = obs.monotonic() - t_crash
            break
        time.sleep(0.05)
    if takeover_s is None:
        gate_msgs.append("standby never took over (waited %.0fs)"
                         % (deadline - t_crash))

    # phase 3: the dead holder's late publish must be fenced off
    zombie_blocked = False
    if takeover_s is not None:
        try:
            store_a.publish(bst.model_to_string(), event="promotion")
        except (StaleLeaseError, LightGBMError):
            zombie_blocked = True
        if not zombie_blocked:
            gate_msgs.append("zombie publish from the crashed trainer "
                             "was NOT fenced off")

    # phase 4: B keeps the pipeline alive — a post-takeover promotion
    # lands and both replicas converge on the newest publish
    converged = False
    deadline = obs.monotonic() + grace
    while obs.monotonic() < deadline:
        published = store_a.state()["last_published_version"]
        if trainer_b.state()["promotions"] >= 1 and all(
                r["server"].fleet_watcher.applied_version == published
                for r in replicas):
            converged = True
            break
        time.sleep(0.1)
    if trainer_b.state()["promotions"] < 1:
        gate_msgs.append("no post-takeover promotion landed")
    published = store_a.state()["last_published_version"]
    if not converged:
        gate_msgs.append("replicas did not converge to v%d after "
                         "failover" % published)

    for t in threads:
        t.join()
    stop_ingest.set()
    ingester.join(timeout=30)
    trainer_b.close(timeout=30)

    versions = [p["version"] for p in store_b.publishes()]
    if len(set(versions)) != len(versions):
        gate_msgs.append("version tokens were reused: %r" % versions)

    rep_docs = []
    for r in replicas:
        st = r["server"].fleet_watcher.state()
        bumps = r["booster"].inner.model_version - r["v0"]
        if bumps != st["swaps"]:
            gate_msgs.append("version bumps != applied swaps (torn swap?)")
        rep_docs.append({"applied_version": st["applied_version"],
                         "swaps": st["swaps"], "version_bumps": bumps})
        r["server"].shutdown()
        r["thread"].join(timeout=30)
        r["server"].close()
    if fails:
        gate_msgs.append("%d request failures" % len(fails))

    result = {
        "bench": "slo_failover",
        "quick": bool(args.quick),
        "lease_ttl_s": ttl,
        "takeover_s": None if takeover_s is None else round(takeover_s, 3),
        "promotions_before_crash": promos_a,
        "promotions_after_takeover": trainer_b.state()["promotions"],
        "zombie_publish_blocked": zombie_blocked,
        "published_version": published,
        "publish_versions": versions,
        "replicas": rep_docs,
        "store_dir": tmp,
        "errors": fails[:5],
        "pass": not gate_msgs,
    }
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    print(json.dumps(result))
    return 0 if result["pass"] else 1


def _run_failover_region(args) -> int:
    """Two-endpoint region drill: a replica follows TWO store-host
    endpoints through a ``MultiEndpointStore`` while labeled traffic is
    forwarded over HTTP to the lease holder; the replica's primary
    endpoint is killed mid-load and the drill gates on failover,
    publish->adopt lag p99, zero dropped forwarded ingest rows, and an
    HTTP-only cold boot from snapshot + tail."""
    import tempfile

    from lightgbm_tpu import obs
    from lightgbm_tpu.fleet import FleetStore, IngestForwarder, \
        MultiEndpointStore, RemoteWriteStore, ReplicaWatcher, \
        bootstrap_model
    from lightgbm_tpu.obs import telemetry
    from lightgbm_tpu.online import OnlineTrainer
    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    clients = args.clients or preset["clients"]
    total = args.requests or preset["requests"]
    rows = args.rows_per_request
    grace = 30 if args.quick else 60
    bst, rng, w = _train_seed(preset)
    telemetry.reset()

    tmp = tempfile.mkdtemp(prefix="lgbtpu_region_bench_")
    store_t = FleetStore(tmp, "default")
    store_t.publish(bst.model_to_string(), event="boot")

    # the leader: an online trainer behind its OWN endpoint — forwarded
    # ingest lands here; snapshot compaction keeps the log cold-bootable
    trainer = OnlineTrainer(bst, trigger_rows=max(256, rows * 8),
                            min_rows=128, shadow_rows=1024,
                            store=store_t, holder_id="trainer",
                            lease_ttl_s=5.0,
                            compact_bytes=400_000, snapshot_rows=2048)
    server_t = PredictServer(bst, port=0, buckets=(64, 256), warmup=True,
                             max_wait_ms=2.0, online=trainer)
    server_t.fleet_store = store_t
    th_t = threading.Thread(target=server_t.serve_forever,
                            name="slo-region-leader", daemon=True)
    th_t.start()
    host, port = server_t.address
    # advertised in the lease doc on the next renew tick — forwarders
    # resolve the leader from there
    trainer.advertise_url = "http://%s:%d" % (host, port)

    gate_msgs = []
    if not trainer.wait_for_lease(grace):
        gate_msgs.append("trainer never went active")

    # two store-host endpoints over the same store dir: the replica's
    # fleet_urls pair, each also forwarding labeled /ingest to the leader
    eps = []
    for i in range(2):
        st = FleetStore(tmp, "default")
        eb, _ = bootstrap_model(st)
        srv = PredictServer(eb, port=0, buckets=(64, 256), warmup=True,
                            max_wait_ms=2.0)
        srv.fleet_store = st
        srv.ingest_forwarder = IngestForwarder(store=st, timeout_s=10.0)
        thr = threading.Thread(target=srv.serve_forever,
                               name="slo-region-ep%d" % i, daemon=True)
        thr.start()
        h, p = srv.address
        eps.append({"server": srv, "thread": thr, "store": st,
                    "base": "http://%s:%d" % (h, p), "alive": True})

    # the serving replica under client load: follows BOTH endpoints
    mstore = MultiEndpointStore([e["base"] for e in eps], timeout_s=10.0,
                                cooldown_base_s=0.1, cooldown_max_s=1.0)
    rb, applied = bootstrap_model(mstore)
    rserver = PredictServer(rb, port=0, buckets=(64, 256), warmup=True,
                            max_wait_ms=2.0)
    rserver.fleet_watcher = ReplicaWatcher(rb, mstore, poll_interval_s=0.1,
                                           applied_version=applied)
    rth = threading.Thread(target=rserver.serve_forever,
                           name="slo-region-replica", daemon=True)
    rth.start()
    rh, rp = rserver.address
    rbase = "http://%s:%d" % (rh, rp)
    v0 = rb.inner.model_version

    # labeled traffic hits the store-host endpoints (which have NO
    # trainer) and must arrive at the leader via forwarding; a chunk is
    # acked only on a 2xx, and acked rows must NEVER be dropped
    acked = {"rows": 0}
    stop_ingest = threading.Event()

    def ingest_loop():
        from urllib.request import Request, urlopen
        k = 0
        while not stop_ingest.is_set():
            Xi = rng.randn(64, preset["features"])
            yi = (Xi @ w > 0).astype("float64")
            body = json.dumps({"rows": Xi.tolist(),
                               "labels": yi.tolist()}).encode()
            for attempt in range(8):
                base = eps[(k + attempt) % 2]["base"]
                req = Request(base + "/ingest", data=body,
                              headers={"Content-Type": "application/json"})
                try:
                    with urlopen(req, timeout=30) as resp:
                        resp.read()
                    acked["rows"] += len(Xi)
                    break
                except Exception:  # noqa: BLE001 - retry on the peer
                    time.sleep(0.05)
            k += 1
            time.sleep(0.02)

    ingester = threading.Thread(target=ingest_loop,
                                name="slo-region-ingest", daemon=True)
    ingester.start()

    fails, sheds = [], []
    threads = [threading.Thread(
        target=_client, name="slo-region-c%d" % i,
        args=(rbase, total // clients, rows,
              json.dumps({"rows": rng.randn(
                  rows, preset["features"]).tolist()}).encode(),
              fails, sheds))
        for i in range(clients)]
    for t in threads:
        t.start()

    # phase 1: at least one promotion must land AND be adopted through
    # the current primary before we kill it
    deadline = obs.monotonic() + grace
    while obs.monotonic() < deadline:
        if trainer.state()["promotions"] >= 1 \
                and rserver.fleet_watcher.state()["swaps"] >= 1:
            break
        time.sleep(0.1)
    promos_pre = trainer.state()["promotions"]
    if promos_pre < 1:
        gate_msgs.append("no promotion landed before the endpoint kill")

    # phase 2: kill the watcher's PRIMARY endpoint mid-load
    primary = mstore.base_url
    victim = next(e for e in eps if e["base"] == primary)
    victim["server"].shutdown()
    victim["thread"].join(timeout=30)
    victim["server"].close()
    victim["alive"] = False
    survivor = next(e for e in eps if e["alive"])

    # phase 3: the pipeline must keep moving through the survivor — a
    # post-kill promotion lands and the replica converges on it
    converged = False
    deadline = obs.monotonic() + grace
    while obs.monotonic() < deadline:
        published = store_t.state()["last_published_version"]
        if trainer.state()["promotions"] > promos_pre \
                and rserver.fleet_watcher.applied_version == published:
            converged = True
            break
        time.sleep(0.1)
    if trainer.state()["promotions"] <= promos_pre:
        gate_msgs.append("no post-kill promotion landed")

    for t in threads:
        t.join()
    stop_ingest.set()
    ingester.join(timeout=30)

    # drain: every acked forwarded chunk is synchronously ingested by
    # the leader before its 2xx, so the counters must already agree
    published = store_t.state()["last_published_version"]
    if not converged:
        gate_msgs.append("replica did not converge to v%d through the "
                         "surviving endpoint" % published)
    switches = telemetry.counter("fleet/endpoint_switches")
    if converged and switches < 1:
        gate_msgs.append("watcher never switched endpoints")

    tstate = trainer.state()
    dropped = max(0, acked["rows"] - tstate["total_ingested_rows"])
    if dropped:
        gate_msgs.append("%d acked ingest rows never reached the "
                         "leader" % dropped)
    lag = telemetry.histogram("fleet/publish_adopt_lag_ms") or {}
    lag_p99 = lag.get("p99")
    if lag_p99 is None:
        gate_msgs.append("no publish->adopt lag samples recorded")
    elif lag_p99 > args.lag_p99_target_ms:
        gate_msgs.append("publish->adopt lag p99 %.1fms > target %.1fms"
                         % (lag_p99, args.lag_p99_target_ms))

    wstate = rserver.fleet_watcher.state()
    bumps = rb.inner.model_version - v0
    if bumps != wstate["swaps"]:
        gate_msgs.append("version bumps (%d) != applied swaps (%d)"
                         % (bumps, wstate["swaps"]))
    if fails:
        gate_msgs.append("%d request failures" % len(fails))

    trainer.close(timeout=30)
    snapshotted = any(e.get("kind") == "compact"
                      and isinstance(e.get("snapshot"), dict)
                      for e in store_t.events())
    if not snapshotted:
        gate_msgs.append("no snapshot compaction landed (log never "
                         "crossed compact_bytes?)")

    # phase 4: HTTP-only cold boot off the survivor — a fresh standby on
    # a "new machine" bootstraps from snapshot + tail, never the disk
    cold_boot_s = None
    cold_replayed = 0
    try:
        t0 = obs.monotonic()
        cold_store = RemoteWriteStore(survivor["base"], timeout_s=10.0)
        cold_bst, _ = bootstrap_model(cold_store)
        cold = OnlineTrainer(cold_bst, trigger_rows=10 ** 9, min_rows=128,
                             shadow_rows=1024, store=cold_store,
                             holder_id="cold-standby")
        cold_boot_s = obs.monotonic() - t0
        cold_replayed = cold.state()["replayed_rows"]
        cold.close(timeout=30)
    except Exception as exc:  # noqa: BLE001 - gate below
        gate_msgs.append("cold boot from snapshot+tail failed: %r" % exc)

    rserver.shutdown()
    rth.join(timeout=30)
    rserver.close()
    for e in eps:
        if e["alive"]:
            e["server"].shutdown()
            e["thread"].join(timeout=30)
            e["server"].close()
    server_t.shutdown()
    th_t.join(timeout=30)
    server_t.close()

    result = {
        "bench": "slo_failover_region",
        "quick": bool(args.quick),
        "killed_endpoint": primary,
        "endpoint_switches": switches,
        "published_version": published,
        "promotions_before_kill": promos_pre,
        "promotions_total": tstate["promotions"],
        "replica": {"applied_version": wstate["applied_version"],
                    "swaps": wstate["swaps"], "version_bumps": bumps},
        "publish_adopt_lag_ms": {k: lag.get(k)
                                 for k in ("p50", "p99")},
        "lag_p99_target_ms": args.lag_p99_target_ms,
        "ingest_rows_acked": acked["rows"],
        "ingest_rows_ingested": tstate["total_ingested_rows"],
        "ingest_rows_dropped": dropped,
        "forwarded_chunks": telemetry.counter("fleet/forwarded_chunks"),
        "snapshot_compactions": store_t.state()["compactions"],
        "cold_boot_s": None if cold_boot_s is None
        else round(cold_boot_s, 3),
        "cold_boot_replayed_rows": cold_replayed,
        "store_dir": tmp,
        "errors": fails[:5],
        "pass": not gate_msgs,
    }
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    print(json.dumps(result))
    return 0 if result["pass"] else 1


def _run_noisy_tenant(args) -> int:
    """Fairness demo/gate: a flooding tenant saturates its quota while a
    quota-respecting tenant keeps its solo latency profile."""
    import numpy as np

    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    rows = args.rows_per_request
    bst, rng, _ = _train_seed(preset)
    server = PredictServer(bst, port=0, buckets=(64, 256), warmup=True,
                           max_wait_ms=2.0,
                           max_queue_rows=8192,
                           tenant_quota_rows=512)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    th = threading.Thread(target=server.serve_forever,
                          name="slo-noisy-serve", daemon=True)
    th.start()

    payload = json.dumps(
        {"rows": rng.randn(rows, preset["features"]).tolist()}).encode()
    big = json.dumps(
        {"rows": rng.randn(64, preset["features"]).tolist()}).encode()
    n_polite = 120 if args.quick else 500

    # phase 1: the polite tenant alone — its fair-share latency profile
    fails, p_sheds, lat_solo = [], [], []
    _client(base, n_polite, rows, payload, fails, p_sheds,
            tenant="polite", lat=lat_solo)

    # phase 2: same workload while a flooding tenant slams its quota
    stop_flood = threading.Event()
    n_sheds = []

    def flood():
        n_fails = []
        while not stop_flood.is_set():
            _client(base, 4, 64, big, n_fails, n_sheds, tenant="noisy")

    flooders = [threading.Thread(target=flood, name="slo-noisy-f%d" % i,
                                 daemon=True) for i in range(2)]
    for f in flooders:
        f.start()
    lat_cont = []
    _client(base, n_polite, rows, payload, fails, p_sheds,
            tenant="polite", lat=lat_cont)
    stop_flood.set()
    for f in flooders:
        f.join(timeout=30)
    stats = server.registry.get().batcher.tenant_stats()
    server.shutdown()
    th.join(timeout=30)
    server.close()

    p99_solo = float(np.percentile(lat_solo, 99)) if lat_solo else 0.0
    p99_cont = float(np.percentile(lat_cont, 99)) if lat_cont else 0.0
    result = {
        "bench": "slo_noisy_tenant",
        "quick": bool(args.quick),
        "polite_requests": n_polite * 2,
        "polite_p99_solo_ms": round(p99_solo, 3),
        "polite_p99_contended_ms": round(p99_cont, 3),
        "polite_429": len(p_sheds),
        "noisy_429": len(n_sheds),
        "fair_p99_factor": args.fair_p99_factor,
        "tenants": stats,
        "errors": fails[:5],
    }
    gate_msgs = []
    if fails:
        gate_msgs.append("%d request failures" % len(fails))
    if p_sheds:
        gate_msgs.append("polite tenant was shed %d times (quota must "
                         "only bite the flooder)" % len(p_sheds))
    if p99_solo > 0 and p99_cont > p99_solo * args.fair_p99_factor:
        gate_msgs.append("polite p99 %.2fms > %.1fx solo %.2fms"
                         % (p99_cont, args.fair_p99_factor, p99_solo))
    result["pass"] = not gate_msgs
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    print(json.dumps(result))
    return 0 if result["pass"] else 1


def _run_ab_dispatch(args) -> int:
    """Interleaved A/B: continuous vs coalesce dispatch over alternating
    closed-loop windows (ABBA), a fresh server + fresh telemetry per
    window — machine drift lands on both arms, so a queue-wait gap is a
    dispatch-mode effect. Gate: pooled continuous queue-wait p99 must be
    materially below coalesce's (``--ab-factor``)."""
    import numpy as np

    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import telemetry
    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    clients = args.clients or preset["clients"]
    per_window = max(clients, (args.requests or preset["requests"]) // 4)
    rows = args.rows_per_request
    bst, rng, _ = _train_seed(preset)
    payload = json.dumps(
        {"rows": rng.randn(rows, preset["features"]).tolist()}).encode()

    order = ["continuous", "coalesce", "coalesce", "continuous"]
    windows = []
    fails_all = []
    for wi, mode in enumerate(order):
        telemetry.reset()
        server = PredictServer(bst, port=0, buckets=(64, 256), warmup=True,
                               max_wait_ms=args.ab_wait_ms,
                               dispatch_mode=mode)
        host, port = server.address
        base = "http://%s:%d" % (host, port)
        th = threading.Thread(target=server.serve_forever,
                              name="slo-ab-serve%d" % wi, daemon=True)
        th.start()
        fails, sheds = [], []
        threads = [threading.Thread(
            target=_client, name="slo-ab-w%d-c%d" % (wi, i),
            args=(base, per_window // clients, rows, payload, fails, sheds))
            for i in range(clients)]
        t0 = obs.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = obs.monotonic() - t0
        server.shutdown()
        th.join(timeout=30)
        server.close()
        fails_all.extend(fails)
        lat = telemetry.histogram("serve/latency_ms") or {}
        qw = telemetry.histogram("serve/queue_wait_ms") or {}
        windows.append({
            "window": wi, "dispatch_mode": mode,
            "elapsed_s": round(elapsed, 3),
            "latency_p99_ms": lat.get("p99"),
            "queue_wait_p50_ms": qw.get("p50"),
            "queue_wait_p99_ms": qw.get("p99"),
        })

    def pooled(mode, key):
        vals = [w[key] for w in windows
                if w["dispatch_mode"] == mode and w[key] is not None]
        return float(np.max(vals)) if vals else 0.0

    cont_qw = pooled("continuous", "queue_wait_p99_ms")
    coal_qw = pooled("coalesce", "queue_wait_p99_ms")
    result = {
        "bench": "slo_ab_dispatch",
        "quick": bool(args.quick),
        "clients": clients,
        "requests_per_window": per_window,
        "rows_per_request": rows,
        "max_wait_ms": args.ab_wait_ms,
        "windows": windows,
        "continuous_queue_wait_p99_ms": round(cont_qw, 3),
        "coalesce_queue_wait_p99_ms": round(coal_qw, 3),
        "continuous_latency_p99_ms": round(
            pooled("continuous", "latency_p99_ms"), 3),
        "coalesce_latency_p99_ms": round(
            pooled("coalesce", "latency_p99_ms"), 3),
        "ab_factor": args.ab_factor,
        "errors": fails_all[:5],
    }
    gate_msgs = []
    if fails_all:
        gate_msgs.append("%d request failures" % len(fails_all))
    if coal_qw <= 0:
        gate_msgs.append("coalesce arm recorded no queue wait")
    elif cont_qw > coal_qw * args.ab_factor:
        gate_msgs.append(
            "continuous queue-wait p99 %.3fms > %.2fx coalesce %.3fms"
            % (cont_qw, args.ab_factor, coal_qw))
    result["pass"] = not gate_msgs
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    print(json.dumps(result))
    return 0 if result["pass"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="slo_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--quick", action="store_true",
                    help="small CPU-friendly workload (CI / laptops)")
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests across all clients")
    ap.add_argument("--rows-per-request", type=int, default=8)
    ap.add_argument("--online", action="store_true",
                    help="run a live refit/promotion loop during the "
                         "measurement window")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet e2e: trainer publishing through a durable "
                         "store, two hot-swapping serving replicas")
    ap.add_argument("--failover", action="store_true",
                    help="failover e2e: active trainer crashes without "
                         "releasing its lease; the standby must take "
                         "over, stay fenced against zombie publishes, "
                         "and re-converge both replicas")
    ap.add_argument("--lag-p99-target-ms", type=float, default=5000.0,
                    help="--failover region drill gate: publish->adopt "
                         "lag p99 bound (ms)")
    ap.add_argument("--noisy-tenant", action="store_true",
                    help="per-tenant fairness gate: flooding tenant vs "
                         "quota-respecting tenant")
    ap.add_argument("--fair-p99-factor", type=float, default=8.0,
                    help="--noisy-tenant bound: contended polite p99 must "
                         "stay within this factor of its solo p99")
    ap.add_argument("--dispatch-mode", default="continuous",
                    choices=("continuous", "coalesce"),
                    help="batcher discipline for the serving stack")
    ap.add_argument("--ab-dispatch", action="store_true",
                    help="interleaved A/B: continuous vs coalesce "
                         "dispatch over alternating closed-loop windows; "
                         "gates on queue-wait p99 reduction")
    ap.add_argument("--ab-wait-ms", type=float, default=5.0,
                    help="--ab-dispatch max_wait_ms for both arms (the "
                         "coalesce company-wait the A/B exposes)")
    ap.add_argument("--ab-factor", type=float, default=0.67,
                    help="--ab-dispatch gate: continuous queue-wait p99 "
                         "must be <= this fraction of coalesce's")
    ap.add_argument("--max-queue-rows", type=int, default=0)
    ap.add_argument("--p99-target-ms", type=float, default=None,
                    help="absolute gate: exit 1 when p99 exceeds this")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="write this run's result JSON to PATH")
    ap.add_argument("--against", default=None, metavar="PATH",
                    help="relative gate: p99 must stay within "
                         "--tolerance x of the recorded baseline p99")
    ap.add_argument("--tolerance", type=float, default=5.0,
                    help="allowed p99 ratio for --against (default 5x: "
                         "a regression gate, not a jitter trap)")
    args = ap.parse_args(argv)

    if args.fleet:
        return _run_fleet(args)
    if args.failover:
        # two drills, two JSON lines: the lease-crash drill, then the
        # two-endpoint region drill — both must pass
        rc = _run_failover(args)
        return _run_failover_region(args) or rc
    if args.noisy_tenant:
        return _run_noisy_tenant(args)
    if args.ab_dispatch:
        return _run_ab_dispatch(args)

    import numpy as np

    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import telemetry
    from lightgbm_tpu.serve import PredictServer

    preset = _preset(args)
    clients = args.clients or preset["clients"]
    total = args.requests or preset["requests"]
    rows = args.rows_per_request
    bst, rng, w = _train_seed(preset)

    online = dict(trigger_rows=max(256, rows * 8), min_rows=128,
                  shadow_rows=1024) if args.online else None
    server = PredictServer(bst, port=0, buckets=(64, 256), warmup=True,
                           max_wait_ms=2.0,
                           max_queue_rows=args.max_queue_rows,
                           dispatch_mode=args.dispatch_mode,
                           online=online)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    serve_thread = threading.Thread(target=server.serve_forever,
                                    name="slo-bench-serve", daemon=True)
    serve_thread.start()

    payload = json.dumps(
        {"rows": rng.randn(rows, preset["features"]).tolist()}).encode()
    fails, sheds = [], []
    stop_ingest = threading.Event()

    def ingest_loop():
        from urllib.request import Request, urlopen
        k = 0
        while not stop_ingest.is_set():
            Xi = rng.randn(64, preset["features"])
            yi = (Xi @ w > 0).astype(np.float64)
            req = Request(base + "/ingest",
                          data=json.dumps({"rows": Xi.tolist(),
                                           "labels": yi.tolist()}).encode(),
                          headers={"Content-Type": "application/json"})
            try:
                urlopen(req, timeout=60).read()
            except Exception:  # noqa: BLE001 - keep feeding
                pass
            k += 1
            time.sleep(0.02)

    shed0 = telemetry.counter("serve/shed")
    req0 = telemetry.counter("serve/requests")
    ingester = None
    if args.online:
        ingester = threading.Thread(target=ingest_loop,
                                    name="slo-bench-ingest", daemon=True)
        ingester.start()
    threads = [threading.Thread(target=_client, name="slo-bench-c%d" % i,
                                args=(base, total // clients, rows,
                                      payload, fails, sheds))
               for i in range(clients)]
    t0 = obs.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = obs.monotonic() - t0
    online_state = None
    if args.online:
        # grace window: let the background trainer land a promotion so
        # the swap-cost histogram (the PERF.md number) gets a sample
        deadline = obs.monotonic() + (10 if args.quick else 30)
        while obs.monotonic() < deadline:
            online_state = server.online.state()
            if online_state["promotions"] >= 1 \
                    or online_state["rejections"] >= 2:
                break
            time.sleep(0.1)
    stop_ingest.set()
    if ingester is not None:
        ingester.join(timeout=30)
    server.shutdown()
    serve_thread.join(timeout=30)
    trainer = server.online if args.online else None
    server.close()          # joins the trainer worker: state is final
    if trainer is not None:
        online_state = trainer.state()

    hist = telemetry.histogram("serve/latency_ms") or {}
    qwait = telemetry.histogram("serve/queue_wait_ms") or {}
    swap = telemetry.histogram("online/promote_swap_ms")
    served = telemetry.counter("serve/requests") - req0
    result = {
        "bench": "slo_serve",
        "quick": bool(args.quick),
        "clients": clients,
        "requests": served,
        "rows_per_request": rows,
        "dispatch_mode": args.dispatch_mode,
        "elapsed_s": round(elapsed, 3),
        "rows_per_s": round(served * rows / max(elapsed, 1e-9), 1),
        "latency_ms": {k: hist.get(k) for k in ("p50", "p90", "p99",
                                                "p999")},
        "queue_wait_ms": {k: qwait.get(k) for k in ("p50", "p90", "p99",
                                                    "p999")},
        "shed": telemetry.counter("serve/shed") - shed0,
        "client_429": len(sheds),
        "errors": fails[:5],
        "online": None if online_state is None else {
            "trains": online_state["trains"],
            "promotions": online_state["promotions"],
            "rejections": online_state["rejections"],
            "train_errors": online_state["errors"],
            "promote_swap_ms": None if swap is None
            else {k: swap.get(k) for k in ("p50", "p99")},
        },
    }

    gate_msgs = []
    p99 = (result["latency_ms"].get("p99") or 0.0)
    if fails:
        gate_msgs.append("%d request failures" % len(fails))
    if args.p99_target_ms is not None and p99 > args.p99_target_ms:
        gate_msgs.append("p99 %.2fms > target %.2fms"
                         % (p99, args.p99_target_ms))
    if args.against:
        with open(args.against) as fh:
            ref = json.load(fh)
        ref_p99 = ref["latency_ms"]["p99"]
        if ref_p99 and p99 > ref_p99 * args.tolerance:
            gate_msgs.append("p99 %.2fms > %.1fx baseline %.2fms"
                             % (p99, args.tolerance, ref_p99))
        result["baseline_p99_ms"] = ref_p99
    result["pass"] = not gate_msgs
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    if args.baseline:
        with open(args.baseline, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
