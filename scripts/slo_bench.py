"""Closed-loop serving SLO benchmark over the real HTTP surface.

Usage:
    python scripts/slo_bench.py --quick                # CPU-sized run
    python scripts/slo_bench.py --quick --online       # + live refit loop
    python scripts/slo_bench.py --baseline SLO_BASELINE.json
    python scripts/slo_bench.py --against SLO_BASELINE.json
    python scripts/slo_bench.py --p99-target-ms 50

Closed loop: N client threads POST /predict against an in-process
``PredictServer`` on an ephemeral port, each sending its next request
only when the previous one answered — the arrival rate adapts to the
server, so the latency distribution is the service time under sustained
concurrency, not queue blow-up under an arbitrary open-loop rate.

With ``--online`` a labeled-ingestion thread feeds POST /ingest while the
clients run, so the reported p99 INCLUDES background train cycles and
promotion swaps — the number the PERF.md promotion-cost note quotes.

Prints ONE JSON line (bench.py style): p50/p90/p99/p999 from the
``serve/latency_ms`` histogram, throughput, shed/error counts, and the
online promotion counters. Gates (exit 1 on miss): ``--p99-target-ms``
absolute, or ``--against BASELINE.json`` relative (p99 within
``--tolerance``x of the recorded baseline). ``--baseline PATH`` records
the run for future ``--against`` gates.
"""
import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _client(base, n, rows, payload, fails, sheds):
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    for _ in range(n):
        req = Request(base + "/predict", data=payload,
                      headers={"Content-Type": "application/json"})
        try:
            with urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
                if len(out["predictions"]) != rows:
                    fails.append("short response")
        except HTTPError as exc:
            (sheds if exc.code == 429 else fails).append(exc.code)
        except Exception as exc:  # noqa: BLE001 - benchmark accounting
            fails.append(repr(exc))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="slo_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--quick", action="store_true",
                    help="small CPU-friendly workload (CI / laptops)")
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests across all clients")
    ap.add_argument("--rows-per-request", type=int, default=8)
    ap.add_argument("--online", action="store_true",
                    help="run a live refit/promotion loop during the "
                         "measurement window")
    ap.add_argument("--max-queue-rows", type=int, default=0)
    ap.add_argument("--p99-target-ms", type=float, default=None,
                    help="absolute gate: exit 1 when p99 exceeds this")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="write this run's result JSON to PATH")
    ap.add_argument("--against", default=None, metavar="PATH",
                    help="relative gate: p99 must stay within "
                         "--tolerance x of the recorded baseline p99")
    ap.add_argument("--tolerance", type=float, default=5.0,
                    help="allowed p99 ratio for --against (default 5x: "
                         "a regression gate, not a jitter trap)")
    args = ap.parse_args(argv)

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import telemetry
    from lightgbm_tpu.serve import PredictServer

    if args.quick:
        preset = dict(train_rows=2000, trees=20, leaves=15, features=10,
                      clients=4, requests=240)
    else:
        preset = dict(train_rows=20000, trees=100, leaves=31, features=20,
                      clients=8, requests=2000)
    clients = args.clients or preset["clients"]
    total = args.requests or preset["requests"]
    rows = args.rows_per_request

    rng = np.random.RandomState(0)
    w = rng.randn(preset["features"])
    X = rng.randn(preset["train_rows"], preset["features"])
    y = (X @ w + 0.2 * rng.randn(len(X)) > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": preset["leaves"]},
                    lgb.Dataset(X, label=y),
                    num_boost_round=preset["trees"])

    online = dict(trigger_rows=max(256, rows * 8), min_rows=128,
                  shadow_rows=1024) if args.online else None
    server = PredictServer(bst, port=0, buckets=(64, 256), warmup=True,
                           max_wait_ms=2.0,
                           max_queue_rows=args.max_queue_rows,
                           online=online)
    host, port = server.address
    base = "http://%s:%d" % (host, port)
    serve_thread = threading.Thread(target=server.serve_forever,
                                    name="slo-bench-serve", daemon=True)
    serve_thread.start()

    payload = json.dumps(
        {"rows": rng.randn(rows, preset["features"]).tolist()}).encode()
    fails, sheds = [], []
    stop_ingest = threading.Event()

    def ingest_loop():
        from urllib.request import Request, urlopen
        k = 0
        while not stop_ingest.is_set():
            Xi = rng.randn(64, preset["features"])
            yi = (Xi @ w > 0).astype(np.float64)
            req = Request(base + "/ingest",
                          data=json.dumps({"rows": Xi.tolist(),
                                           "labels": yi.tolist()}).encode(),
                          headers={"Content-Type": "application/json"})
            try:
                urlopen(req, timeout=60).read()
            except Exception:  # noqa: BLE001 - keep feeding
                pass
            k += 1
            time.sleep(0.02)

    shed0 = telemetry.counter("serve/shed")
    req0 = telemetry.counter("serve/requests")
    ingester = None
    if args.online:
        ingester = threading.Thread(target=ingest_loop,
                                    name="slo-bench-ingest", daemon=True)
        ingester.start()
    threads = [threading.Thread(target=_client, name="slo-bench-c%d" % i,
                                args=(base, total // clients, rows,
                                      payload, fails, sheds))
               for i in range(clients)]
    t0 = obs.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = obs.monotonic() - t0
    online_state = None
    if args.online:
        # grace window: let the background trainer land a promotion so
        # the swap-cost histogram (the PERF.md number) gets a sample
        deadline = obs.monotonic() + (10 if args.quick else 30)
        while obs.monotonic() < deadline:
            online_state = server.online.state()
            if online_state["promotions"] >= 1 \
                    or online_state["rejections"] >= 2:
                break
            time.sleep(0.1)
    stop_ingest.set()
    if ingester is not None:
        ingester.join(timeout=30)
    server.shutdown()
    serve_thread.join(timeout=30)
    trainer = server.online if args.online else None
    server.close()          # joins the trainer worker: state is final
    if trainer is not None:
        online_state = trainer.state()

    hist = telemetry.histogram("serve/latency_ms") or {}
    swap = telemetry.histogram("online/promote_swap_ms")
    served = telemetry.counter("serve/requests") - req0
    result = {
        "bench": "slo_serve",
        "quick": bool(args.quick),
        "clients": clients,
        "requests": served,
        "rows_per_request": rows,
        "elapsed_s": round(elapsed, 3),
        "rows_per_s": round(served * rows / max(elapsed, 1e-9), 1),
        "latency_ms": {k: hist.get(k) for k in ("p50", "p90", "p99",
                                                "p999")},
        "shed": telemetry.counter("serve/shed") - shed0,
        "client_429": len(sheds),
        "errors": fails[:5],
        "online": None if online_state is None else {
            "trains": online_state["trains"],
            "promotions": online_state["promotions"],
            "rejections": online_state["rejections"],
            "train_errors": online_state["errors"],
            "promote_swap_ms": None if swap is None
            else {k: swap.get(k) for k in ("p50", "p99")},
        },
    }

    gate_msgs = []
    p99 = (result["latency_ms"].get("p99") or 0.0)
    if fails:
        gate_msgs.append("%d request failures" % len(fails))
    if args.p99_target_ms is not None and p99 > args.p99_target_ms:
        gate_msgs.append("p99 %.2fms > target %.2fms"
                         % (p99, args.p99_target_ms))
    if args.against:
        with open(args.against) as fh:
            ref = json.load(fh)
        ref_p99 = ref["latency_ms"]["p99"]
        if ref_p99 and p99 > ref_p99 * args.tolerance:
            gate_msgs.append("p99 %.2fms > %.1fx baseline %.2fms"
                             % (p99, args.tolerance, ref_p99))
        result["baseline_p99_ms"] = ref_p99
    result["pass"] = not gate_msgs
    if gate_msgs:
        result["gate_failures"] = gate_msgs
    if args.baseline:
        with open(args.baseline, "w") as fh:
            json.dump(result, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
