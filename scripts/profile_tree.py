"""Decompose the partitioned tree builder's per-iteration cost on the TPU.

Chained-execution methodology (see calibrate.py): host syncs through the
tunnel cost 100-700 ms, so each primitive is chained K times inside one jit
with a data dependency and per-op cost = (t_K - t_1)/(K-1).

Measures, at the bench shape (N=2M, F=28, B=256, L=255):
  - build_tree_partitioned end-to-end (ms per tree)
  - hist16_segment at several segment sizes (slope + fixed cost)
  - partition_segment at several segment sizes (slope + per-chunk cost)
  - find_best_split per call
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

N = int(os.environ.get("PROF_N", 2_000_000))
F = 28
B = 256
L = int(os.environ.get("PROF_LEAVES", 255))


# trusted wall per PERF.md discipline: warm once, then time one call
# ended by a forced 1-element transfer (obs.timed_sync)
timed = obs.timed_sync


def chain_cost(make_chain, K=4):
    f1 = make_chain(1)
    fK = make_chain(K)
    t1 = min(timed(f1), timed(f1))
    tK = min(timed(fK), timed(fK))
    return (tK - t1) / (K - 1)


def main():
    from lightgbm_tpu.learner import (SerialTreeLearner, build_tree_partitioned)
    from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper, find_best_split
    from lightgbm_tpu.ops.histogram import hist16_segment
    from lightgbm_tpu.ops.partition import (pack_rows, partition_segment,
                                            DEFAULT_CH)

    print("devices:", jax.devices())
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(N, F)), jnp.uint8)
    g = rng.randn(N).astype(np.float32)
    h = np.abs(rng.randn(N)).astype(np.float32) + 0.1
    ghc = jnp.asarray(np.stack([g, h, np.ones(N, np.float32)], axis=1))
    meta = FeatureMeta(
        num_bins=jnp.full((F,), B, jnp.int32),
        movable_missing=jnp.zeros((F,), bool),
        missing_bin=jnp.zeros((F,), jnp.int32),
        is_categorical=jnp.zeros((F,), bool),
        monotone=jnp.zeros((F,), jnp.int8),
        penalty=jnp.ones((F,), jnp.float32),
        cegb_coupled=jnp.zeros((F,), jnp.float32),
    )
    hp = SplitHyper()
    fmask = jnp.ones((F,), bool)
    key = jax.random.PRNGKey(0)
    cegb_used = jnp.zeros((F,), bool)

    # ---------------- full tree ----------------
    def make_tree(k):
        @jax.jit
        def f(bins, ghc):
            def body(c, _):
                log = build_tree_partitioned(
                    bins, ghc + c * 1e-30, meta, fmask, key, cegb_used, hp,
                    num_leaves=L, num_bin=B)
                return jnp.float32(log.num_splits), None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(bins, ghc)

    per = chain_cost(make_tree, K=3)
    print(f"build_tree_partitioned N={N} L={L}: {per*1e3:.1f} ms/tree")

    # ---------------- histogram segment ----------------
    guard = DEFAULT_CH
    work0 = pack_rows(jnp.pad(bins, ((guard, guard), (0, 0))),
                      jnp.pad(ghc, ((guard, guard), (0, 0))))
    work = jnp.stack([work0, jnp.zeros_like(work0)])

    def make_hist(k, cnt):
        @jax.jit
        def f(work):
            def body(c, _):
                hg = hist16_segment(work, jnp.int32(0),
                                    jnp.int32(guard) + c.astype(jnp.int32) * 0,
                                    jnp.int32(cnt), num_bins=B, num_feat=F)
                return jnp.sum(hg) * 1e-30, None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(work)

    for cnt in (N, N // 4, 65536, 8192, 2048):
        per = chain_cost(partial(make_hist, cnt=cnt), K=4)
        print(f"hist16_segment cnt={cnt}: {per*1e3:.2f} ms "
              f"({cnt/per/1e6:.0f} M rows/s)")

    # ---------------- partition segment ----------------
    table = jnp.asarray(rng.rand(B) < 0.5)

    def make_part(k, cnt):
        @jax.jit
        def f(work):
            def body(carry, _):
                w, c = carry
                w2, lt = partition_segment(
                    w, c % 2, jnp.int32(guard), jnp.int32(cnt),
                    jnp.int32(3), table)
                return (w2, 1 - c), None
            (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None, length=k)
            return w[0, 0, 0]
        return lambda: f(work)

    for cnt in (N, N // 4, 65536, 8192, 2048):
        per = chain_cost(partial(make_part, cnt=cnt), K=4)
        nch = (cnt + DEFAULT_CH - 1) // DEFAULT_CH
        print(f"partition_segment cnt={cnt}: {per*1e3:.2f} ms "
              f"({cnt/per/1e6:.0f} M rows/s, {per/nch*1e6:.1f} us/chunk)")

    # ---------------- split scan ----------------
    hist = jnp.asarray(rng.randn(F, B, 3).astype(np.float32))
    psum = jnp.sum(hist, axis=(0, 1)) / F

    def make_split(k):
        @jax.jit
        def f(hist):
            def body(c, _):
                info = find_best_split(hist + c * 1e-30, psum, meta, fmask, hp)
                return info.gain * 1e-30, None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(hist)

    per = chain_cost(make_split, K=16)
    print(f"find_best_split (F={F},B={B}): {per*1e6:.0f} us/call")


if __name__ == "__main__":
    main()
