"""Decompose the fused per-iteration cost at bench shape on the TPU.

Chained-execution methodology (calibrate.py): per-op = (t_K - t_1)/(K-1).
Measures the full fused iteration and its components: tree build, the
end-of-tree assign_leaves routing pass, leaf_values_by_row, gradients,
row packing, and the partition/histogram kernels at representative sizes.
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

N = int(os.environ.get("PROF_N", 2_000_000))


# trusted wall per PERF.md discipline: warm once, then time one call
# ended by a forced 1-element transfer (obs.timed_sync)
timed = obs.timed_sync


def chain_cost(make_chain, K=4):
    f1 = make_chain(1)
    fK = make_chain(K)
    t1 = min(timed(f1), timed(f1))
    tK = min(timed(fK), timed(fK))
    return (tK - t1) / (K - 1)


def main():
    import lightgbm_tpu as lgb
    from bench import make_higgs_like
    from lightgbm_tpu.fused import FusedTrainer
    from lightgbm_tpu.learner import assign_leaves, leaf_values_by_row
    from lightgbm_tpu.basic import Booster

    print("devices:", jax.devices())
    X, y = make_higgs_like(N)
    params = {
        "objective": "binary", "num_leaves": 255, "max_bin": 255,
        "learning_rate": 0.1, "verbosity": -1, "tpu_iter_block": 1,
    }
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    b = Booster(params=dict(params), train_set=ds)
    g = b.inner
    ft = FusedTrainer(g)
    lrn = g.learner
    obj = g.objective
    build = lrn.make_build_fn()
    kw = lrn.build_kwargs()
    print("build kwargs:", {k: v for k, v in kw.items()
                            if k in ("hist_chunk", "part_chunk", "hist_mode",
                                     "part_kernel")})

    # ---------------- full fused iteration ----------------
    from lightgbm_tpu.fused import _obj_array_state
    ostate = _obj_array_state(obj)

    def make_blockk(k):
        g.config.tpu_iter_block = k
        ft2 = FusedTrainer(g)
        fn = ft2._block_fn(k)

        def run():
            out = fn(g.train_score.score, jnp.asarray(g._cegb_used),
                     g._key, jnp.int32(0), lrn.bins, lrn.meta, ostate)
            return out[0][0]
        return run

    per = chain_cost(make_blockk, K=4)
    print(f"fused iteration: {per*1e3:.1f} ms/iter "
          f"({N/per/1e6:.1f} M rows/s)")

    # ---------------- one tree build (incl. assign_leaves) ----------------
    score0 = g.train_score.score
    gg, hh = obj.get_gradients(score0)
    ghc = jnp.stack([gg, hh, jnp.ones_like(gg)], axis=1)
    fmask = jnp.ones((lrn.bins.shape[1],), bool)
    key = jax.random.PRNGKey(0)
    cegb_used = jnp.zeros((lrn.bins.shape[1],), bool)

    def make_tree(k):
        @jax.jit
        def f(bins, ghc):
            def body(c, _):
                log = build(bins, ghc + c * 1e-30, lrn.meta, fmask, key,
                            cegb_used)
                return jnp.float32(log.num_splits), None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(lrn.bins, ghc)

    per_tree = chain_cost(make_tree, K=3)
    print(f"build_tree(+assign): {per_tree*1e3:.1f} ms/tree")

    # ---------------- assign_leaves ----------------
    log1 = jax.jit(build)(lrn.bins, ghc, lrn.meta, fmask, key, cegb_used)
    jax.block_until_ready(log1.row_leaf)

    def make_assign(k):
        @jax.jit
        def f(bins, log):
            def body(c, _):
                rl = assign_leaves(bins, log._replace(
                    num_splits=log.num_splits + c.astype(jnp.int32) * 0),
                    has_categorical=False, bundle=None)
                return jnp.float32(rl[0]), None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(lrn.bins, log1)

    per = chain_cost(make_assign, K=3)
    print(f"assign_leaves: {per*1e3:.1f} ms/tree")

    # ---------------- leaf_values_by_row ----------------
    def make_lvbr(k):
        @jax.jit
        def f(rl, lv):
            def body(c, _):
                v = leaf_values_by_row(lv + c * 1e-30, rl, 255)
                return v[0], None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(log1.row_leaf, log1.leaf_value)

    per = chain_cost(make_lvbr, K=6)
    print(f"leaf_values_by_row: {per*1e3:.1f} ms")

    # ---------------- gradients ----------------
    def make_grad(k):
        @jax.jit
        def f(score):
            def body(c, _):
                gg, hh = obj.get_gradients(score + c * 1e-30)
                return gg[0], None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(score0)

    per = chain_cost(make_grad, K=6)
    print(f"gradients: {per*1e3:.1f} ms")

    # ---------------- pack + buffer write ----------------
    from lightgbm_tpu.ops.partition import pack_rows, work_spec
    guard, width = work_spec(lrn.bins.shape[1], False, kw["part_kernel"],
                             kw["part_chunk"], kw["hist_chunk"])
    npad = N + 2 * guard
    wbuf0 = jnp.zeros((2, npad, width), jnp.uint8)

    def make_pack(k):
        @jax.jit
        def f(bins, ghc, wbuf):
            def body(c, _):
                w0 = pack_rows(jnp.pad(bins, ((guard, guard), (0, 0))),
                               jnp.pad(ghc + c * 1e-30, ((guard, guard), (0, 0))))
                w = wbuf.at[0, :, :w0.shape[1]].set(w0)
                return w[0, guard, 0].astype(jnp.float32), None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
            return c
        return lambda: f(lrn.bins, ghc, wbuf0)

    per = chain_cost(make_pack, K=4)
    print(f"pack+buffer write: {per*1e3:.1f} ms")


if __name__ == "__main__":
    main()
