"""Calibrate TPU kernel costs through the axon tunnel.

block_until_ready is unreliable over the tunnel and any host sync costs
~100-700 ms, so every measurement chains k executions inside one jit
(lax.scan with data dependency) and compares k=1 vs k=K to cancel the
fixed overhead: per-op = (t_K - t_1) / (K - 1).
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs


def timed(fn, *args):
    """Run once (compiled), sync via scalar transfer, return seconds."""
    fn(*args)
    return obs.timed_sync(lambda: fn(*args))


def chain_cost(make_chain, K=8):
    f1 = make_chain(1)
    fK = make_chain(K)
    t1 = min(timed(f1), timed(f1))
    tK = min(timed(fK), timed(fK))
    return (tK - t1) / (K - 1)


def main():
    print("devices:", jax.devices())
    rng = np.random.RandomState(0)

    # ---------- matmul sanity ----------
    a = jnp.asarray(rng.randn(8192, 8192), jnp.bfloat16)
    b = jnp.asarray(rng.randn(8192, 8192), jnp.bfloat16)

    def make_mm(k):
        @jax.jit
        def f(a, b):
            def body(c, _):
                return jnp.tanh(c @ b), None
            c, _ = jax.lax.scan(body, a, None, length=k)
            return c.sum()
        return lambda: f(a, b)

    per = chain_cost(make_mm)
    print(f"matmul 8192^3 bf16: {per*1e3:.2f} ms -> {2*8192**3/per/1e12:.1f} TFLOP/s")

    # ---------- histogram variants ----------
    from lightgbm_tpu.ops.histogram import build_histogram

    N, F, B = 2_000_000, 28, 256
    bins = jnp.asarray(rng.randint(0, B, size=(N, F)), jnp.uint8)
    ghc = jnp.asarray(rng.randn(N, 3), jnp.float32)

    def make_hist(k, chunk, mxu_bf16):
        @jax.jit
        def f(bins, ghc):
            def body(acc, i):
                h = build_histogram(bins, ghc + acc[0, 0, :][None], B, chunk,
                                    mxu_bf16=mxu_bf16)
                return h * 1e-9, None
            acc0 = jnp.zeros((F, B, 3), jnp.float32)
            acc, _ = jax.lax.scan(body, acc0, None, length=k)
            return acc.sum()
        return lambda: f(bins, ghc)

    for mxu_bf16 in (False, True):
        for chunk in (8192, 32768, 131072):
            per = chain_cost(partial(make_hist, chunk=chunk, mxu_bf16=mxu_bf16), K=4)
            print(f"hist einsum bf16={int(mxu_bf16)} chunk={chunk}: {per*1e3:.1f} ms "
                  f"({N/per/1e6:.0f} M rows/s, {N*F*B*3*2/per/1e12:.2f} TFLOP/s)")

    # ---------- gather ----------
    C = 65536
    idx0 = jnp.asarray(rng.randint(0, N, size=(C,)), jnp.int32)

    def make_gather(k):
        @jax.jit
        def f(idx):
            def body(carry, _):
                s, idx = carry
                g1 = bins[idx]
                g2 = ghc[idx]
                s2 = s + g1.astype(jnp.float32).sum() + g2.sum()
                idx2 = (idx + 1) % N
                return (s2, idx2), None
            (s, _), _ = jax.lax.scan(body, (jnp.float32(0), idx), None, length=k)
            return s
        return lambda: f(idx0)

    per = chain_cost(make_gather, K=16)
    print(f"gather {C} rows (F=28 u8 + 3 f32): {per*1e3:.2f} ms "
          f"({C/per/1e6:.0f} M rows/s)")

    # ---------- compaction ----------
    mask0 = jnp.asarray(rng.rand(N) < 0.25)

    def make_compact(k, how):
        @jax.jit
        def f(mask):
            def body(carry, _):
                s, mask = carry
                if how == "scatter":
                    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
                    buf = jnp.zeros((N,), jnp.int32)
                    buf = buf.at[jnp.where(mask, pos, N)].set(
                        jnp.arange(N, dtype=jnp.int32), mode="drop")
                    out = buf
                elif how == "argsort":
                    out = jnp.argsort(~mask, stable=True).astype(jnp.int32)
                else:
                    out = jnp.cumsum(mask.astype(jnp.int32))
                s2 = s + out[0] + out[-1]
                return (s2, jnp.roll(mask, 1)), None
            (s, _), _ = jax.lax.scan(body, (jnp.int32(0), mask), None, length=k)
            return s
        return lambda: f(mask0)

    for how in ("cumsum", "scatter", "argsort"):
        per = chain_cost(partial(make_compact, how=how), K=4)
        print(f"compact {how} N={N}: {per*1e3:.2f} ms")


if __name__ == "__main__":
    main()
