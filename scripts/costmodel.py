"""Op-level device cost profile of one fused block via jax.profiler.

The axon tunnel's profiler returns deterministic per-op costs (repeat runs
reproduce to 0.01 ms), which makes it a reliable A/B instrument while
wall-clock through the tunnel fluctuates 30-50% run to run.

env: PROF_N (2M), PROF_K (3 iters/block), and any lightgbm params via
PROF_PARAMS as a JSON dict (merged over the bench defaults).
"""
import collections
import glob
import gzip
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def profile_block(params_extra=None, n=None, k=None, top=18,
                  rank=False):
    import lightgbm_tpu as lgb
    import lightgbm_tpu.fused as F
    from bench import make_higgs_like, make_mslr_like
    from lightgbm_tpu.basic import Booster

    n = n or int(os.environ.get("PROF_N", 2_000_000))
    k = k or int(os.environ.get("PROF_K", 3))
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "learning_rate": 0.1, "verbosity": -1, "tpu_iter_block": k}
    if rank:
        X, y, group = make_mslr_like(n)
        params["objective"] = "lambdarank"
        kw = {"group": group}
    else:
        X, y = make_higgs_like(n)
        kw = {}
    params.update(params_extra or {})
    params.update(json.loads(os.environ.get("PROF_PARAMS", "{}")))
    ds = lgb.Dataset(X, label=y, **kw)
    ds.construct()
    b = Booster(params=dict(params), train_set=ds)
    g = b.inner
    ft = F.FusedTrainer(g)
    fn = ft._block_fn(k)
    ostate = F._obj_array_state(g.objective)
    args = (g.train_score.score, jnp.asarray(g._cegb_used), g._key,
            jnp.int32(0), g.learner.bins, g.learner.meta, ostate)
    out = fn(*args)
    jax.block_until_ready(out)
    tdir = "/tmp/jaxtrace_cm"
    shutil.rmtree(tdir, ignore_errors=True)
    with jax.profiler.trace(tdir):
        out = fn(*args)
        jax.block_until_ready(out)
    path = sorted(glob.glob(tdir + "/plugins/profile/*/*.trace.json.gz"))[-1]
    data = json.load(gzip.open(path, "rt"))
    events = data["traceEvents"]
    pids = {e["pid"]: e["args"].get("name", "") for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tot = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        if "TPU" not in pids.get(e["pid"], ""):
            continue
        tot[e["name"]] += e.get("dur", 0)
        cnt[e["name"]] += 1
    rows = tot.most_common(top)
    for name, d in rows:
        print(f"{d/1e3/k:9.2f} ms/iter  x{cnt[name]/k:8.1f}  {name[:84]}")
    return tot, cnt, k


if __name__ == "__main__":
    profile_block(rank=os.environ.get("PROF_RANK", "") == "1")
