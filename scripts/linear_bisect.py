"""Interleaved A/B: host per-leaf ridge solve loop vs the batched device fit.

The linear-leaf fit is L independent ridge solves over branch-path
features. The host oracle (boosting._fit_linear_tree) gathers each leaf's
rows and calls ``np.linalg.solve`` sequentially — O(L) host round trips of
Python-side gather + BLAS. The device kernel (lightgbm_tpu/linear/fit.py)
accumulates ALL leaves' normal equations with chunked one-hot matmuls and
solves them in one batched ``jnp.linalg.solve`` — two MXU contractions per
chunk, one solve, one transfer.

Measurement discipline (PERF.md): single process, A/B interleaved
trial-by-trial, best-of-R, every device wall ends in a forced 1-element
``np.asarray(..)[:1]`` transfer. Parity (f32 device vs f64 host) is
reported alongside so a fast-but-wrong kernel can't sneak through.

On a CPU backend the batched fit runs through XLA:CPU against numpy's
native BLAS — those numbers are correctness-only, never quote them as
perf. The speedup claim only means anything on a TPU backend, where the
host loop additionally pays L device->host residual transfers.

Usage: python scripts/linear_bisect.py [n_rows] [num_leaves] [k_feats] [n_feats]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.linear.fit import fit_leaves

REPS = 5
LAM = 0.01


def build(n, L, k, f, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    row_leaf = rng.randint(0, L, n).astype(np.int32)
    g = rng.randn(n).astype(np.float64)
    h = np.ones(n, np.float64)
    feat_idx = np.zeros((L, k), np.int32)
    for l in range(L):
        feat_idx[l] = np.sort(rng.choice(f, k, replace=False))
    feat_mask = np.ones((L, k), bool)
    return X, row_leaf, g, h, feat_idx, feat_mask


def host_fit(X, row_leaf, g, h, feat_idx, feat_mask):
    """The oracle's sequential shape: per leaf, gather rows, build the
    design matrix, one f64 ridge solve (boosting._fit_linear_tree)."""
    L, k = feat_idx.shape
    betas = np.zeros((L, k + 1))
    for l in range(L):
        rows = np.flatnonzero(row_leaf == l)
        Z = np.column_stack([X[rows][:, feat_idx[l]],
                             np.ones(len(rows))])
        hw = h[rows]
        A = Z.T @ (Z * hw[:, None])
        A[np.arange(k), np.arange(k)] += LAM
        b = Z.T @ g[rows]
        betas[l] = -np.linalg.solve(A, b)
    return betas


def main(n, L, k, f):
    backend = jax.default_backend()
    X, row_leaf, g, h, feat_idx, feat_mask = build(n, L, k, f)
    Xd = jnp.asarray(X, jnp.float32)
    rl = jnp.asarray(row_leaf, jnp.int32)
    gd = jnp.asarray(g, jnp.float32)
    hd = jnp.asarray(h, jnp.float32)
    fid = jnp.asarray(feat_idx, jnp.int32)
    fmd = jnp.asarray(feat_mask, jnp.bool_)
    lam = jnp.asarray(LAM, jnp.float32)
    print(f"backend={backend} n={n} L={L} k={k} F={f}")

    # warmup: compile the batched fit, prime BLAS
    beta_d, ok_d = fit_leaves(Xd, rl, gd, hd, fid, fmd, lam)
    beta_dh = np.asarray(beta_d, np.float64)
    assert bool(np.asarray(ok_d).all()), "device fit declined some leaves"
    beta_h = host_fit(X, row_leaf, g, h, feat_idx, feat_mask)

    print("parity |beta_dev - beta_host| max: %.3e"
          % np.max(np.abs(beta_dh - beta_h)))

    best = {"host": np.inf, "device": np.inf}
    for _ in range(REPS):                    # A, B, A, B ... interleaved
        with obs.wall("linear_bisect/host", record=False) as w:
            host_fit(X, row_leaf, g, h, feat_idx, feat_mask)
        best["host"] = min(best["host"], w.seconds)
        with obs.wall("linear_bisect/device", record=False) as w:
            bd, _ = fit_leaves(Xd, rl, gd, hd, fid, fmd, lam)
            np.asarray(bd)[:1]               # forced transfer: trusted end
        best["device"] = min(best["device"], w.seconds)

    for name, s in best.items():
        print(f"{name:8s} {s * 1e3:9.3f} ms  ({n / s / 1e6:7.1f} M rows/s)")
    print(f"device speedup: {best['host'] / best['device']:.2f}x "
          f"(L={L} sequential host solves -> 1 batched device solve)")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    L = int(sys.argv[2]) if len(sys.argv) > 2 else 63
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    f = int(sys.argv[4]) if len(sys.argv) > 4 else 28
    main(n, L, k, f)
