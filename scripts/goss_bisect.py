"""Interleaved A/B: GOSS row compaction vs the dense-mask oracle.

Measures what ISSUE 17 landed — after `make_sampler` zeroes the
out-of-bag rows, the compact path sorts the in-bag survivors to the
front (ops/partition.py compact_rows_by_inbag) and every downstream
per-split pass (partition, histogram, leaf routing) runs over the
static ceil((top_rate+other_rate)*N)-row slice instead of all N padded
rows — under measurement discipline v2 (PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (the mutated work buffer and alternating plane parity), so the
  tunnel cannot deduplicate bit-identical re-executions;
- every wall ends in a forced 1-element device_get;
- per-split time = (t_K - t_1) / (K - 1), best-of-R, which cancels the
  dispatch + sync overhead shared by both chain lengths;
- a byte-parity gate runs FIRST: compact on/off `lgb.train` must give
  identical model_to_string() before any timing is trusted.

This is the validation gate for the tpu_goss_compact auto knob: auto
stays "off" until a v5e session runs this script, confirms parity plus
a wall win at the production shape, and flips the knob (or lets the
run ledger carry the measured answer forward).

The compaction itself is pure XLA (argsort + take + lax.cond), so the
op-level A/B runs on any backend; train walls with the pallas
partition stream need a TPU (or LGBTPU_PALLAS_INTERPRET=1 — interpreter
numbers are correctness-only, never quote them as perf).

Usage: python scripts/goss_bisect.py [n_rows] [num_feat] [train_rows]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import hist16_segment

CH = 1024        # partition/histogram chunk
NUM_BIN = 64
REPS = 5
K = 4
TOP_RATE, OTHER_RATE = 0.2, 0.1


def parity_gate(n, f, seed=3):
    """Byte-identical models, compact off vs on, before any timing."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 63, "max_bin": NUM_BIN,
              "verbosity": -1, "boosting": "goss", "top_rate": TOP_RATE,
              "other_rate": OTHER_RATE, "learning_rate": 0.5,
              "tpu_iter_block": 2}
    out = {}
    for mode in ("off", "on"):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(dict(params, tpu_goss_compact=mode), ds,
                        num_boost_round=6)
        out[mode] = bst.model_to_string()
    same = out["off"] == out["on"]
    print("parity gate (n=%d, 6 rounds, lr=0.5): %s"
          % (n, "BYTE-IDENTICAL" if same else "DIVERGED"))
    return same


def build_rows(n, f, seed=0):
    """Dense rows-layout work buffer with a GOSS-like in-bag mask, and its
    compacted counterpart (in-bag survivors sorted to the front)."""
    rng = np.random.RandomState(seed)
    guard, width = P.work_spec(f, False, "xla", CH, CH, layout="rows")
    bins = jnp.asarray(rng.randint(0, NUM_BIN, (n, f)).astype(np.uint8))
    ghc = rng.randn(n, 3).astype(np.float32)
    inbag = rng.rand(n) < (TOP_RATE + OTHER_RATE)
    ghc[:, 2] = inbag
    ghc[:, 0] *= inbag
    ghc[:, 1] = np.abs(ghc[:, 1]) * inbag
    ghc = jnp.asarray(ghc)
    m = P.goss_compact_rows(n, TOP_RATE, OTHER_RATE)
    bc, gc, _ = P.compact_rows_by_inbag(bins, ghc, m)

    def pack(b, g):
        pad = ((guard, guard), (0, 0))
        w0 = P.pack_rows(jnp.pad(b, pad), jnp.pad(g, pad))
        if w0.shape[1] < width:
            w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
        return jnp.stack([w0, jnp.zeros_like(w0)])

    return pack(bins, ghc), pack(bc, gc), guard, m


def make_pass(work, guard, rows, f):
    """One per-split pass over `rows` rows: partition + histogram (the two
    passes compaction shrinks). XLA kernels, so any backend measures."""
    go_left = jnp.asarray(np.arange(NUM_BIN) < NUM_BIN // 3)

    def make(k):
        @jax.jit
        def run(w):
            def body(carry, _):
                w, c, acc = carry
                w, lt = P.partition_segment(
                    w, c % 2, jnp.int32(guard), jnp.int32(rows),
                    jnp.int32(3), go_left, ch=CH)
                h = hist16_segment(w, 1 - c % 2, jnp.int32(guard),
                                   jnp.int32(rows), num_bins=NUM_BIN,
                                   num_feat=f, chunk=CH)
                return (w, 1 - c, acc + h[0, 0, 0] + lt), None
            (w, _, acc), _ = jax.lax.scan(
                body, (w, jnp.int32(0), jnp.float32(0)), None, length=k)
            return w.reshape(-1)[:1], acc
        return lambda: run(work)
    return make


def train_wall(compact, n, f, iters=10, seed=3):
    """Wall of one warm GOSS `lgb.train` with the knob forced on/off."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": NUM_BIN,
              "verbosity": -1, "boosting": "goss", "top_rate": TOP_RATE,
              "other_rate": OTHER_RATE, "tpu_iter_block": 5,
              "tpu_goss_compact": compact}
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=5)        # warmup/compile
    def run():
        with obs.wall("bisect/train_goss_" + compact, record=False) as w:
            bst = lgb.train(dict(params), ds, num_boost_round=iters)
            obs.sync(bst.inner.train_score.score)   # trusted wall end
        return w.seconds
    return run


def main(n, f, train_n):
    backend = jax.default_backend()
    if not parity_gate(min(n, 4000), min(f, 8)):
        print("REFUSING to time a diverging configuration.")
        return
    wd, wc, guard, m = build_rows(n, f)
    print(f"backend={backend} n={n} F={f} compact_rows={m} "
          f"({100.0 * m / n:.0f}% of dense) bins={NUM_BIN}")

    res = obs.ab_interleaved(
        [("goss/dense_pass", make_pass(wd, guard, n, f)),
         ("goss/compact_pass", make_pass(wc, guard, m, f))],
        reps=REPS, k=K)
    print()
    for name, per in res.items():
        print(f"{name:24s} {per * 1e3:8.3f} ms/split")
    base = res.get("goss/dense_pass")
    comp = res.get("goss/compact_pass")
    if base and comp:
        verdict = ("WIN — flip tpu_goss_compact auto to on"
                   if base / comp > 1.02 else "NO WIN — keep auto=off")
        print(f"\ncompaction speedup: {base / comp:.2f}x ({verdict})")

    if train_n > 0:
        runs = [("train/off", train_wall("off", train_n, f)),
                ("train/on", train_wall("on", train_n, f))]
        best = {name: np.inf for name, _ in runs}
        for _ in range(3):
            for name, run in runs:           # A, B, A, B per rep
                best[name] = min(best[name], run())
        print()
        for name, w in best.items():
            print(f"{name:24s} {w:8.3f} s  (10 iters, n={train_n})")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    train_n = int(sys.argv[3]) if len(sys.argv) > 3 else 300_000
    main(n, f, train_n)
