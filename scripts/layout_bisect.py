"""Interleaved A/B: rows (2, Npad, W) vs planes (2, W, Npad) work layout.

Measures the three hot paths the layout change touches — partition,
segment histogram, and pack(+root fold) — under measurement discipline v2
(PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (alternating src/dst plane parity and the mutated work buffer), so the
  tunnel cannot deduplicate bit-identical re-executions;
- every wall ends in a forced 1-element device_get (`np.asarray(..)[:1]`)
  — block_until_ready does not reliably synchronize through the tunnel;
- per-op time = (t_K - t_1) / (K - 1), best-of-R, which cancels the
  dispatch + sync overhead shared by both chain lengths.

On a TPU backend the pallas kernels run natively; elsewhere they are
skipped unless LGBTPU_PALLAS_INTERPRET=1 (interpreter numbers are
correctness-only — never quote them as perf).

Usage: python scripts/layout_bisect.py [n_rows] [num_feat]
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import hist16_segment, hist16_segment_planes

CH = 1024        # partition chunk (pallas optimum, PERF.md round 5)
HCH = 4096       # histogram chunk
REPS = 5
K = 4


def chain_per_op(make):
    """Best-of-REPS (t_K - t_1)/(K - 1) for one chained-scan bench."""
    return obs.ab_interleaved([("x", make)], reps=REPS, k=K)["x"]


def build_inputs(n, f, num_bin=256, seed=0):
    rng = np.random.RandomState(seed)
    guard_r = P.guard_rows(CH)
    guard_p = CH + 2 * P.PLANE_ALIGN
    guard = max(guard_r, guard_p)
    npad_p = ((n + 2 * guard + 127) // 128) * 128
    bins = np.zeros((npad_p, f), np.uint8)
    bins[guard:guard + n] = rng.randint(0, num_bin, (n, f))
    ghc = np.zeros((npad_p, 3), np.float32)
    ghc[guard:guard + n] = rng.randn(n, 3).astype(np.float32)
    ghc[guard:guard + n, 2] = 1.0
    w_r = P.pack_rows(jnp.asarray(bins), jnp.asarray(ghc))
    if w_r.shape[1] % 128:           # rows pallas kernel wants 128-mult width
        w_r = jnp.pad(w_r, ((0, 0), (0, 128 - w_r.shape[1] % 128)))
    w_p = P.pack_planes(jnp.asarray(bins), jnp.asarray(ghc))
    wpad = (-w_p.shape[0]) % 32
    if wpad:
        w_p = jnp.pad(w_p, ((0, wpad), (0, 0)))
    work_r = jnp.stack([w_r, jnp.zeros_like(w_r)])
    work_p = jnp.stack([w_p, jnp.zeros_like(w_p)])
    table = jnp.asarray(rng.rand(num_bin) < 0.5)
    return work_r, work_p, table, guard, bins, ghc


def part_make(fn, work, guard, n, table, ch):
    def make(k):
        @jax.jit
        def f(work):
            def body(carry, _):
                w, c = carry
                w2, _lt = fn(w, c % 2, jnp.int32(guard), jnp.int32(n),
                             jnp.int32(3), table, ch=ch)
                return (w2, 1 - c), None
            (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None,
                                     length=k)
            return w.reshape(-1)[:1]
        return lambda: f(work)
    return make


def hist_make(fn, work, guard, n, f_real, shift):
    def make(k):
        @jax.jit
        def f(work):
            def body(carry, _):
                s, acc = carry
                h = fn(work, jnp.int32(0), jnp.int32(guard + s % 64),
                       jnp.int32(n - 64), num_bins=256, num_feat=f_real,
                       chunk=HCH)
                return (s + shift, acc + h[0, 0, 0]), None
            (_, acc), _ = jax.lax.scan(body, (jnp.int32(0), jnp.float32(0)),
                                       None, length=k)
            return acc.reshape(1)
        return lambda: f(work)
    return make


def pack_make_rows(bins, ghc, guard, n, f_real, work_shape):
    binsd, ghcd = jnp.asarray(bins), jnp.asarray(ghc)

    def make(k):
        @jax.jit
        def f(b, g):
            def body(carry, _):
                s, acc = carry
                w0 = P.pack_rows(b, g + s)          # changing carry -> no dedup
                work = jnp.zeros(work_shape, jnp.uint8).at[
                    0, :, :w0.shape[1]].set(w0)
                h = hist16_segment(work, jnp.int32(0), jnp.int32(guard),
                                   jnp.int32(n), num_bins=256,
                                   num_feat=f_real, chunk=HCH)
                return (s + 1.0, acc + h[0, 0, 0]), None
            (_, acc), _ = jax.lax.scan(body, (jnp.float32(0),
                                              jnp.float32(0)), None, length=k)
            return acc.reshape(1)
        return lambda: f(binsd, ghcd)
    return make


def pack_make_planes(bins, ghc, guard, n, f_real, work_shape):
    binsd = jnp.asarray(bins[guard:guard + n])
    ghcd = jnp.asarray(ghc[guard:guard + n])

    def make(k):
        @jax.jit
        def f(b, g):
            def body(carry, _):
                s, acc = carry
                work = jnp.zeros(work_shape, jnp.uint8)
                work, root = P.pack_planes_fold_root(
                    work, b, g + s, guard, num_bins=256, exact=True,
                    chunk=HCH)
                return (s + 1.0, acc + root[0, 0, 0]), None
            (_, acc), _ = jax.lax.scan(body, (jnp.float32(0),
                                              jnp.float32(0)), None, length=k)
            return acc.reshape(1)
        return lambda: f(binsd, ghcd)
    return make


def main(n, f):
    backend = jax.default_backend()
    pallas_ok = backend in ("tpu", "axon") or P._INTERPRET
    work_r, work_p, table, guard, bins, ghc = build_inputs(n, f)
    print(f"backend={backend} n={n} F={f} row_w={work_r.shape[2]} "
          f"planes_w={work_p.shape[1]} guard={guard} "
          f"(pallas {'on' if pallas_ok else 'SKIPPED — no TPU'})")

    pairs = [
        ("part/rows/xla",
         part_make(P.partition_segment, work_r, guard, n, table, CH)),
        ("part/planes/xla",
         part_make(P.partition_segment_planes, work_p, guard, n, table, CH)),
    ]
    if pallas_ok:
        pairs += [
            ("part/rows/pallas",
             part_make(P.partition_segment_fused, work_r, guard, n, table,
                       CH)),
            ("part/planes/pallas",
             part_make(P.partition_segment_planes_fused, work_p, guard, n,
                       table, CH)),
        ]
    pairs += [
        ("hist/rows/xla",
         hist_make(hist16_segment, work_r, guard, n, f, 1)),
        ("hist/planes/xla",
         hist_make(hist16_segment_planes, work_p, guard, n, f, 1)),
        ("pack+root/rows",
         pack_make_rows(bins, ghc, guard, n, f, work_r.shape)),
        ("pack+root/planes(folded)",
         pack_make_planes(bins, ghc, guard, n, f, work_p.shape)),
    ]
    res = obs.ab_interleaved(pairs, reps=REPS, k=K)
    for name, per in res.items():
        print(f"{name:28s} {per * 1e3:8.3f} ms  ({n / per / 1e6:7.1f} M rows/s)")
    for stem in ("part", "hist", "pack+root"):
        rows = {k: v for k, v in res.items() if k.startswith(stem)}
        base = rows.get(f"{stem}/rows/xla") or rows.get(f"{stem}/rows")
        if base:
            for k, v in rows.items():
                print(f"  {k:26s} {base / v:5.2f}x vs {stem} rows baseline")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    main(n, f)
