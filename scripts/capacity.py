"""Single-chip capacity demonstration (VERDICT r4 item 6).

Trains at BIG_N rows x 28 features on one chip and records peak HBM.
PERF.md's capacity model claims ~40M rows at Higgs width on a 16 GB v5e;
this script demonstrates >= 30M (0.75x the claimed ceiling).

Usage: python scripts/capacity.py [rows]   (default 30M)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BIG_N = int(sys.argv[1]) if len(sys.argv) > 1 else 30_000_000


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import lightgbm_tpu as lgb
    from bench import make_higgs_like

    from lightgbm_tpu import obs
    with obs.wall("capacity/datagen", record=False) as w:
        X, y = make_higgs_like(BIG_N)
    print("datagen %.1fs" % w.seconds, flush=True)
    with obs.wall("capacity/construct", record=False) as w:
        ds = lgb.Dataset(X, label=y)
        ds.construct()
    print("construct %.1fs" % w.seconds, flush=True)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "learning_rate": 0.1, "verbosity": -1, "metric": ["auc"],
              "tpu_iter_block": 5}
    with obs.wall("capacity/train", record=False) as w:
        bst = lgb.train(dict(params), ds, num_boost_round=10)
    train_s = w.seconds
    (_, _, auc, _), = bst.eval_train()
    stats = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        pass
    peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
    print("rows=%d train(10 iters)=%.1fs auc=%.4f peak_hbm=%s"
          % (BIG_N, train_s, auc,
             ("%.2f GB" % (peak / 1e9)) if peak else "unavailable"),
          flush=True)


if __name__ == "__main__":
    main()
