"""Correctness + perf harness for the v2 fused partition kernel
(lightgbm_tpu/ops/partition.py _partition_kernel). Run on TPU.

Design vs v1 (ops/partition.py _partition_kernel):
- compaction permutation matmuls at SB=256 instead of CH (8x less MXU work
  per row: the perm cost is CH*W MACs/row);
- left/right frontier rows accumulate in circular VMEM stages (2*CH + CH
  physical rows; the top CH is a wrap margin) and flush to HBM as ALIGNED
  PURE WRITES of CH rows — no per-chunk read-modify-write windows and no
  lout.wait()/rin serialization;
- neighbor bytes at the aligned edges are prefilled once per call; the
  final sub-CH leftovers drain as full tiles plus one overlapping RMW tile.

Row order inside a leaf segment is insignificant (histograms are
order-free; sub-splits re-partition), and the kernel preserves exactly the
SET of rows per side; neighbor rows outside [start, start+cnt) are
byte-preserved.
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

ALIGN = 32

from lightgbm_tpu.ops.partition import partition_segment_fused


def partition_segment_v2(work, src_plane, start, cnt, feat, go_left, *,
                         ch=1024, sb=256):
    """The integrated library kernel (ops/partition.py) under test."""
    return partition_segment_fused(work, src_plane, start, cnt, feat,
                                   go_left, ch=ch, sb=sb)


# ---------------------------------------------------------------- testing

def ref_partition(work_np, plane, start, cnt, feat, table):
    """NumPy reference: stable set-preserving partition."""
    seg = work_np[plane, start:start + cnt]
    go = table[seg[:, feat].astype(np.int64)]
    left = seg[go]
    right = seg[~go]
    out = work_np.copy()
    out[1 - plane, start:start + cnt] = np.concatenate([left, right], axis=0)
    return out, len(left)


def main():
    print("devices:", jax.devices())
    rng = np.random.RandomState(0)
    ch = int(os.environ.get("CH", 1024))
    sb = int(os.environ.get("SB", 256))
    W = int(os.environ.get("W", 128))
    F = 28
    B = 256
    guard = ch + 2 * ALIGN
    jit_part = jax.jit(partial(partition_segment_v2, ch=ch, sb=sb))

    # correctness across many segment shapes
    N = 200_000
    npad = N + 2 * guard
    base = rng.randint(0, 256, size=(2, npad, W)).astype(np.uint8)
    table = (rng.rand(B) < 0.47)
    work = jnp.asarray(base)
    tab = jnp.asarray(table)
    ok = True
    for (start, cnt) in [(guard, N), (guard + 5, 33), (guard, 1),
                         (guard + 31, 2), (guard + 1000, 65536),
                         (guard + 7, 4096), (guard + 12345, 99991),
                         (guard + 3, ch - 1), (guard, ch),
                         (guard + 17, ch + 1), (guard, 2 * ch + 77)]:
        for plane in (0, 1):
            w2, lt = jit_part(work, jnp.int32(plane), jnp.int32(start),
                              jnp.int32(cnt), jnp.int32(3), tab)
            w2 = np.asarray(w2)
            refw, ref_lt = ref_partition(base, plane, start, cnt, 3, table)
            lt = int(lt)
            # left/right row SETS must match (order within side is free)
            got_l = w2[1 - plane, start:start + lt]
            got_r = w2[1 - plane, start + lt:start + cnt]
            ref_l = refw[1 - plane, start:start + lt]
            ref_r = refw[1 - plane, start + lt:start + cnt]
            def rowset(a):
                return set(map(bytes, a))
            sl = lt == ref_lt and rowset(got_l) == rowset(ref_l) \
                and rowset(got_r) == rowset(ref_r)
            # neighbor bytes preserved on the destination plane
            nb = (w2[1 - plane, :start] == base[1 - plane, :start]).all() \
                and (w2[1 - plane, start + cnt:]
                     == base[1 - plane, start + cnt:]).all()
            # source plane untouched
            sp = (w2[plane] == base[plane]).all()
            if not (sl and nb and sp):
                ok = False
                print(f"FAIL start={start} cnt={cnt} plane={plane}: "
                      f"lt={lt}/{ref_lt} sets={sl} neigh={nb} src={sp}")
    print("correctness:", "OK" if ok else "FAILED")
    if not ok:
        return

    # benchmark vs v1 at bench shape
    from lightgbm_tpu.ops.partition import partition_segment_fused
    N = 2_000_000
    npad = N + 2 * guard
    base = rng.randint(0, 256, size=(2, npad, W)).astype(np.uint8)
    work = jnp.asarray(base)

    # trusted wall per PERF.md discipline (obs.timed_sync): warm once,
    # then time one call ended by a forced 1-element transfer
    timed = obs.timed_sync

    def chain(K, fn, cnt, ch_):
        @jax.jit
        def f(work):
            def body(carry, _):
                w, c = carry
                w2, lt = fn(w, c % 2, jnp.int32(guard), jnp.int32(cnt),
                            jnp.int32(3), tab)
                return (w2, 1 - c), None
            (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None,
                                     length=K)
            return w[0, guard, 0]
        return lambda: f(work)

    for cnt in (N, 65536, 8192):
        for name, fn in (("v2", partial(partition_segment_v2, ch=ch, sb=sb)),):
            t1 = min(timed(chain(1, fn, cnt, ch)) for _ in range(3))
            tK = min(timed(chain(9, fn, cnt, ch)) for _ in range(3))
            per = (tK - t1) / 8
            print(f"{name} cnt={cnt}: {per*1e6:9.1f} us "
                  f"({per/cnt*1e9:6.2f} ns/row)")


if __name__ == "__main__":
    main()
