"""A/B the hi/lo split width of the segment histogram einsum.

Current: hi=B/16 (SH), lo=16 -> log_ = lo_oh*ch materializes 16*NCH wide.
Candidates: lo=8 (SH=32), lo=4 (SH=64). Narrower lo shrinks the
materialized (C, F, LO*NCH) product and raises the hi-side matmul M dim
(better MXU tiling); wider hi grows the (C, F, SH) one-hot.
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

N = int(os.environ.get("PROF_N", 2_000_000))
F = int(os.environ.get("PROF_F", 28))
B = 256
CHUNK = int(os.environ.get("PROF_CHUNK", 4096))


# trusted wall per PERF.md discipline: warm once, then time one call
# ended by a forced 1-element transfer (obs.timed_sync)
timed = obs.timed_sync


def chain_cost(make_chain, K=4):
    f1 = make_chain(1)
    fK = make_chain(K)
    t1 = min(timed(f1) for _ in range(3))
    tK = min(timed(fK) for _ in range(3))
    return (tK - t1) / (K - 1)


def _split_bf16(x):
    hi = jax.lax.optimization_barrier(x.astype(jnp.bfloat16))
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def hist_chunk_lo(cb, cgm, lo_w: int):
    dt = jnp.bfloat16
    sh = B // lo_w
    shift = {2: 1, 4: 2, 8: 3, 16: 4}[lo_w]
    hi = (cb >> shift).astype(jnp.uint8)
    lo = (cb & (lo_w - 1)).astype(jnp.uint8)
    hi_oh = (hi[:, :, None] == jnp.arange(sh, dtype=jnp.uint8)).astype(dt)
    lo_oh = (lo[:, :, None] == jnp.arange(lo_w, dtype=jnp.uint8))
    g_hi, g_lo = _split_bf16(cgm[:, 0])
    h_hi, h_lo = _split_bf16(cgm[:, 1])
    ch = jnp.stack([g_hi, g_lo, h_hi, h_lo,
                    cgm[:, 2].astype(jnp.bfloat16)], axis=1)
    c, f = cb.shape
    log_ = (lo_oh[:, :, :, None].astype(dt)
            * ch[:, None, None, :].astype(dt)).reshape(c, f, lo_w * 5)
    return jnp.einsum("cfh,cfx->fhx", hi_oh, log_,
                      preferred_element_type=jnp.float32)


def hist_seg(work, start, cnt, lo_w):
    f = F
    sh = B // lo_w
    nchunks = (cnt + CHUNK - 1) // CHUNK
    width = work.shape[1]

    def body(i, acc):
        off = start + i * CHUNK
        cw = jax.lax.dynamic_slice(work, (off, 0), (CHUNK, width))
        cb = cw[:, :f]
        gb = cw[:, f:f + 12].reshape(CHUNK, 3, 4)
        cg = jax.lax.bitcast_convert_type(gb, jnp.float32)
        rows_left = cnt - i * CHUNK
        valid = jnp.arange(CHUNK, dtype=jnp.int32) < rows_left
        cgm = cg * valid[:, None].astype(jnp.float32)
        return acc + hist_chunk_lo(cb, cgm, lo_w)

    acc = jax.lax.fori_loop(0, nchunks, body,
                            jnp.zeros((f, sh, lo_w * 5), jnp.float32))
    h = acc.reshape(f, sh, lo_w, 5).reshape(f, sh * lo_w, 5)[:, :B]
    return jnp.stack([h[..., 0] + h[..., 1], h[..., 2] + h[..., 3],
                      h[..., 4]], axis=-1)


def main():
    print("devices:", jax.devices(), "N=%d F=%d chunk=%d" % (N, F, CHUNK))
    rng = np.random.RandomState(0)
    bins = rng.randint(0, B, size=(N, F)).astype(np.uint8)
    ghc = np.stack([rng.randn(N), np.abs(rng.randn(N)) + 0.1,
                    np.ones(N)], axis=1).astype(np.float32)
    gb = ghc.view(np.uint8).reshape(N, 12)
    work = jnp.asarray(np.concatenate([bins, gb], axis=1))

    ref = None
    for lo_w in (16, 8, 4):
        def make(k, lo_w=lo_w):
            @jax.jit
            def f(work):
                def body(c, _):
                    # non-foldable carry dependency: keeps XLA from
                    # hoisting the loop-invariant body out of the scan
                    start = (c > 1e30).astype(jnp.int32)
                    hg = hist_seg(work, start, N, lo_w)
                    return c + jnp.sum(hg) * 1e-30, None
                c, _ = jax.lax.scan(body, jnp.float32(0), None, length=k)
                return c
            return lambda: f(work)

        per = chain_cost(make, K=9)
        print(f"lo_w={lo_w}: {per*1e3:.2f} ms ({N/per/1e6:.0f} M rows/s, "
              f"{per/N*1e9*1e3/F:.3f} ns/row*feat)")
        h = jax.jit(partial(hist_seg, lo_w=lo_w))(work, jnp.int32(0),
                                                  jnp.int32(N))
        h = np.asarray(h)
        if ref is None:
            ref = h
        else:
            print("   max abs diff vs lo16:", np.abs(h - ref).max())


if __name__ == "__main__":
    main()
