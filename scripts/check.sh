#!/bin/sh
# Pre-commit gate, layered by cost:
#
#   check.sh            lint (full repo) + lint tests + the fast
#                       serve/online/obs/one-kernel/forest-kernel
#                       tier-1 subset (a few min CPU; the one-kernel
#                       and forest-kernel parity trains run under the
#                       pallas interpreter)
#   check.sh --fast     lint only files changed vs git + lint tests
#
# Every mode (including --fast) fails on baseline drift: lint.py exits
# nonzero on net-new findings AND on stale lint_baseline.json entries
# (a frozen finding whose source line no longer exists — the baseline
# must shrink monotonically; run scripts/lint.py --update-baseline).
#   check.sh --fleet    lint + lint tests + the fleet/online/serve fast
#                       subset (durability/fairness/rollback plus the
#                       failover/compaction/transport hardening tests,
#                       the fleet-observatory status/trace tests and
#                       the region control-plane suite: remote write
#                       surface, multi-endpoint failover, ingest
#                       forwarding, snapshot bootstrap)
#   check.sh --slo      everything above, plus the closed-loop serving
#                       SLO bench gated against SLO_BASELINE.json
#   check.sh --ledger   everything above, plus the run-ledger regression
#                       gate: train the fixed CI workload (appends one
#                       ledger entry) and fail on >25% train-wall
#                       regression vs the previous matching entry
set -e
cd "$(dirname "$0")/.."

LINT_ARGS=""
RUN_SUBSET=1
RUN_FLEET=0
RUN_SLO=0
RUN_LEDGER=0
case "$1" in
    --fast)   LINT_ARGS="--changed"; RUN_SUBSET=0 ;;
    --fleet)  RUN_SUBSET=0; RUN_FLEET=1 ;;
    --slo)    RUN_SLO=1 ;;
    --ledger) RUN_LEDGER=1 ;;
esac

echo "== graftlint =="
python scripts/lint.py $LINT_ARGS

echo "== lint tests =="
JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py -q -m 'not slow'

if [ "$RUN_SUBSET" = 1 ]; then
    echo "== serve/online/obs/linear/one-kernel/forest/goss-mxu fast tests =="
    JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
        tests/test_serve.py tests/test_online.py \
        tests/test_obs.py tests/test_trace.py \
        tests/test_linear_device.py tests/test_one_kernel.py \
        tests/test_forest_kernel.py tests/test_goss_compact.py \
        tests/test_hist_mxu.py
fi

if [ "$RUN_FLEET" = 1 ]; then
    echo "== fleet/online/serve fast tests =="
    JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
        tests/test_fleet.py tests/test_failover.py \
        tests/test_fleet_obs.py tests/test_control.py \
        tests/test_online.py tests/test_serve.py
fi

if [ "$RUN_SLO" = 1 ]; then
    echo "== serving SLO bench (vs SLO_BASELINE.json) =="
    JAX_PLATFORMS=cpu python scripts/slo_bench.py --quick \
        --against SLO_BASELINE.json
fi

if [ "$RUN_LEDGER" = 1 ]; then
    echo "== run-ledger regression gate (scripts/ledger.py) =="
    LEDGER_PATH="${LEDGER_PATH:-lgbtpu_ledger.jsonl}"
    JAX_PLATFORMS=cpu python scripts/ledger.py train --path "$LEDGER_PATH"
    JAX_PLATFORMS=cpu python scripts/ledger.py gate --path "$LEDGER_PATH" \
        --metric extra.train_s --tolerance "${LEDGER_TOLERANCE:-0.25}"
fi
