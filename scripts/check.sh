#!/bin/sh
# Pre-commit gate: full-repo graftlint + the linter's own test suite.
# Both are jax-light and finish in well under a minute on CPU.
set -e
cd "$(dirname "$0")/.."

echo "== graftlint (full repo) =="
python scripts/lint.py

echo "== lint tests =="
JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py -q
