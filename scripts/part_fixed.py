"""Measure the fused partition kernel's fixed per-call cost.

Chains many partition calls at several segment sizes in ONE jit; the
per-call time vs cnt line gives (fixed, per-row) directly.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

from lightgbm_tpu.ops.partition import (guard_rows, pack_rows,
                                        partition_segment_fused, work_spec)

N = int(os.environ.get("PN", 1 << 21))
F = 28
CH = int(os.environ.get("PCH", 1024))
REPS = 254

rng = np.random.RandomState(0)
bins = rng.randint(0, 255, size=(N, F), dtype=np.uint8)
ghc = rng.randn(N, 3).astype(np.float32)
guard, width = work_spec(F, False, "pallas", CH, 4096)
pad = ((guard, guard), (0, 0))
w0 = pack_rows(jnp.pad(jnp.asarray(bins), pad), jnp.pad(jnp.asarray(ghc), pad))
w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
work = jnp.stack([w0, jnp.zeros_like(w0)])
table = jnp.asarray(rng.rand(255) < 0.5)


@jax.jit
def chain(work, cnt):
    def body(i, carry):
        work, tot = carry
        work, lt = partition_segment_fused(
            work, jax.lax.rem(i, 2), jnp.int32(guard), cnt,
            jax.lax.rem(i, F), table, ch=CH)
        return work, tot + lt

    return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))


for cnt in (256, 1024, 4096, 16384, 65536, 262144):
    obs.sync(chain(work, jnp.int32(cnt)))
    best = 1e9
    for _ in range(3):
        with obs.wall("part_fixed/chain", record=False) as w:
            obs.sync(chain(work, jnp.int32(cnt)))
        best = min(best, w.seconds)
    per = best / REPS * 1e6
    print("cnt=%7d  %8.1f us/call  (%5.2f ns/row)" %
          (cnt, per, per * 1e3 / cnt))
