"""Bisect the partition kernel's ~400us fixed cost: strip pieces, measure.

The hardware harness behind the ``tpu_part_chunk`` auto knob (rows per
partition compaction launch): the 1024-pallas / 2048-xla defaults are
the chunk points this bisect measured on v5e.
"""
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

N = 1 << 20
CH = 1024
SB = 256
W = 128
REPS = 254
ALIGN = 32

work = jnp.zeros((2, N, W), jnp.uint8)
table = jnp.zeros((1, 255), jnp.float32)


def make(variant):
    def kern(sref, w_in, tref, w_ref, lt_ref, tril, cin, pre, lstage, rstage,
             lfb, rfb, sem):
        f32 = jnp.float32
        src_plane = sref[0]
        start = sref[1]
        cnt = sref[2]
        feat = sref[3]
        dst_plane = 1 - src_plane
        lbase0 = (start // ALIGN) * ALIGN
        head = start - lbase0
        tot = head + cnt
        nchunks = (tot + CH - 1) // CH

        if variant >= 1:  # tril init
            row_i = jax.lax.broadcasted_iota(jnp.int32, (SB, SB), 0)
            col_i = jax.lax.broadcasted_iota(jnp.int32, (SB, SB), 1)
            tril[:] = jnp.clip(row_i - col_i, 0, 1).astype(f32) \
                .astype(jnp.bfloat16)

        if variant >= 2:  # prefill DMAs
            p0 = pltpu.make_async_copy(
                w_in.at[dst_plane, pl.ds(lbase0, ALIGN), :], pre.at[0],
                sem.at[2])
            p0.start()
            p0.wait()
            lstage[0:ALIGN, :] = pre[0].astype(jnp.int32).astype(f32)

        if variant >= 3:  # chunk loop: DMA in + trivial consume + DMA out
            def body(i, acc):
                slot = jax.lax.rem(i, 2)
                cp = pltpu.make_async_copy(
                    w_in.at[src_plane,
                            pl.ds(((start + i * CH) // ALIGN) * ALIGN, CH), :],
                    cin.at[slot], sem.at[slot])
                cp.start()
                cp.wait()
                if variant >= 4:  # u8 -> f32 convert
                    cf = cin[slot].astype(jnp.int32).astype(f32)
                    lstage[0:CH, :] = cf
                if variant >= 5:  # route: col extract + one-hot table
                    cf = lstage[0:CH, :]
                    lane_w = jax.lax.broadcasted_iota(jnp.int32, (CH, W), 1)
                    col = jnp.sum(jnp.where(lane_w == feat, cf, 0.0), axis=1,
                                  keepdims=True)
                    bin_l = jax.lax.broadcasted_iota(jnp.int32, (CH, 255), 1)
                    oh = (1 - jnp.clip(jnp.abs(bin_l - col.astype(jnp.int32)),
                                       0, 1)).astype(f32)
                    go = jnp.sum(oh * tref[:], axis=1, keepdims=True) > 0.5
                    acc = acc + jnp.sum(go.astype(jnp.int32))
                if variant >= 6:  # 4x perm matmuls + stage blends
                    cf = lstage[0:CH, :]
                    iota_sb8 = jax.lax.broadcasted_iota(
                        jnp.int32, (SB + 8, 1), 0)
                    for s in range(CH // SB):
                        sub = cf[s * SB:(s + 1) * SB]
                        flags = jnp.concatenate(
                            [jnp.ones((SB, 1), jnp.bfloat16),
                             jnp.zeros((SB, 1), jnp.bfloat16)], axis=1)
                        ranks = jax.lax.dot(tril[:], flags,
                                            preferred_element_type=f32)
                        dest = ranks[:, 0:1].astype(jnp.int32)
                        j_i = jax.lax.broadcasted_iota(
                            jnp.int32, (SB + 8, SB), 0)
                        perm = (1 - jnp.clip(
                            jnp.abs(j_i - dest.reshape(1, SB)), 0, 1)) \
                            .astype(f32).astype(jnp.bfloat16)
                        out = jax.lax.dot(perm, sub.astype(jnp.bfloat16),
                                          preferred_element_type=f32)
                        rstage[pl.ds(s * (SB + 8), SB + 8)] = out
                # write out one tile
                ob = rstage[0:CH, :].astype(jnp.int32).astype(jnp.uint8)
                lfb[0] = ob
                wr = pltpu.make_async_copy(
                    lfb.at[0],
                    w_ref.at[dst_plane,
                             pl.ds(((start + i * CH) // ALIGN) * ALIGN,
                                   CH), :],
                    sem.at[4])
                wr.start()
                wr.wait()
                return acc

            acc = jax.lax.fori_loop(0, nchunks, body, jnp.int32(0))
            lt_ref[0] = acc
        else:
            lt_ref[0] = cnt

    return kern


def bench(variant):
    kern = make(variant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        scratch_shapes=[
            pltpu.VMEM((SB, SB), jnp.bfloat16),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.VMEM((2, ALIGN, W), jnp.uint8),
            pltpu.VMEM((3 * CH, W), jnp.float32),
            pltpu.VMEM((3 * CH, W), jnp.float32),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.VMEM((2, CH, W), jnp.uint8),
            pltpu.SemaphoreType.DMA((8,)),
        ],
    )

    @jax.jit
    def chain(work, cnt):
        def body(i, carry):
            work, tot = carry
            scalars = jnp.stack([jax.lax.rem(i, 2), jnp.int32(CH),
                                 cnt, jax.lax.rem(i, 28)])
            w2, lt = pl.pallas_call(
                kern, name="part_bisect", grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                           jax.ShapeDtypeStruct((1,), jnp.int32)],
                input_output_aliases={1: 0},
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",),
                    vmem_limit_bytes=100 * 1024 * 1024),
            )(scalars, work, table)
            return w2, tot + lt[0]
        return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))

    for cnt in (256, 16384):
        obs.sync(chain(work, jnp.int32(cnt)))
        best = 1e9
        for _ in range(2):
            with obs.wall("part_bisect/variant", record=False) as w:
                obs.sync(chain(work, jnp.int32(cnt)))
            best = min(best, w.seconds)
        print("variant=%d cnt=%6d: %7.1f us/call" %
              (variant, cnt, best / REPS * 1e6))


for v in (0, 1, 2, 3, 4, 5, 6):
    bench(v)
