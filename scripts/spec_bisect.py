"""Which pallas_call spec feature costs ~350us/call?"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

REPS = 254
N = 1 << 20
W = 128
work = jnp.zeros((2, N, W), jnp.uint8)
table = jnp.zeros((1, 255), jnp.float32)


def bench(name, scratch, smem_out, semN, vlimit, dimsem, vmem_in):
    def kern(sref, w_in, tref, w_ref, lt_ref, *scr):
        if smem_out:
            lt_ref[0] = sref[2]
        else:
            lt_ref[...] = jnp.full((8, 128), sref[2], jnp.int32)

    out_specs = [pl.BlockSpec(memory_space=pltpu.HBM),
                 pl.BlockSpec(memory_space=pltpu.SMEM if smem_out
                              else pltpu.VMEM)]
    scratch_shapes = []
    if scratch:
        scratch_shapes = [
            pltpu.VMEM((256, 256), jnp.bfloat16),
            pltpu.VMEM((2, 1024, W), jnp.uint8),
            pltpu.VMEM((2, 32, W), jnp.uint8),
            pltpu.VMEM((3 * 1024, W), jnp.float32),
            pltpu.VMEM((3 * 1024, W), jnp.float32),
            pltpu.VMEM((2, 1024, W), jnp.uint8),
            pltpu.VMEM((2, 1024, W), jnp.uint8),
        ]
    if semN:
        scratch_shapes.append(pltpu.SemaphoreType.DMA((semN,)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                  pl.BlockSpec(memory_space=pltpu.VMEM if vmem_in
                               else pltpu.HBM)],
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    cp = {}
    if dimsem:
        cp["dimension_semantics"] = ("arbitrary",)
    if vlimit:
        cp["vmem_limit_bytes"] = 100 * 1024 * 1024

    @jax.jit
    def chain(work, cnt):
        def body(i, carry):
            work, tot = carry
            scalars = jnp.stack([jax.lax.rem(i, 2), jnp.int32(1024),
                                 cnt, jax.lax.rem(i, 28)])
            w2, lt = pl.pallas_call(
                kern, name="spec_bisect", grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                           jax.ShapeDtypeStruct((1,) if smem_out else (8, 128),
                                              jnp.int32)],
                input_output_aliases={1: 0},
                compiler_params=pltpu.CompilerParams(**cp) if cp else None,
            )(scalars, work, table)
            return w2, tot + lt.reshape(-1)[0]
        return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))

    obs.sync(chain(work, jnp.int32(256)))
    best = 1e9
    for _ in range(2):
        with obs.wall("spec_bisect/stage", record=False) as w:
            obs.sync(chain(work, jnp.int32(256)))
        best = min(best, w.seconds)
    print("%-44s %7.1f us/call" % (name, best / REPS * 1e6))


bench("bare (no scratch, vmem out, no sem)", False, False, 0, False, False, True)
bench("+ smem out", False, True, 0, False, False, True)
bench("+ dma sem(8)", False, True, 8, False, False, True)
bench("+ dimension_semantics", False, True, 8, False, True, True)
bench("+ vmem_limit", False, True, 8, False, True, True)
bench("+ big scratch", True, True, 8, True, True, True)
bench("scratch only", True, False, 0, False, False, True)
bench("sem only", False, False, 1, False, False, True)
