"""Phase breakdown of the driver-visible bench wall (VERDICT r4 item 1).

Runs the binary bench shape and reports where every second goes:
dataset construction, warmup (trace/compile vs execute), the timed train's
dispatch / logs-transfer / host-tree phases, and the pure device time of one
fused block (block_until_ready around the cached block fn).

Usage: python scripts/profile_wall.py [N_ROWS] [N_ITER]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 60
BLOCK = int(os.environ.get("BENCH_BLOCK", 20))


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from lightgbm_tpu import obs
    with obs.wall("profile/import") as w:
        import lightgbm_tpu as lgb
        from lightgbm_tpu.utils.timer import global_timer
    t_import = w.seconds

    rng = np.random.RandomState(7)
    with obs.wall("profile/datagen") as wt:
        X = rng.randn(N, 28).astype(np.float32)
        w = rng.randn(28) / np.sqrt(28)
        logit = X @ w + 0.5 * np.sin(X[:, 0] * 2) * X[:, 1] \
            + 0.3 * rng.randn(N)
        y = (logit > 0).astype(np.float64)
        X = X.astype(np.float64)
    t_datagen = wt.seconds

    params = {
        "objective": "binary", "num_leaves": 255, "max_bin": 255,
        "learning_rate": 0.1, "verbosity": -1, "metric": ["auc"],
        "tpu_iter_block": BLOCK,
    }
    with obs.wall("profile/construct") as wt:
        ds = lgb.Dataset(X, label=y)
        ds.construct()
    t_construct = wt.seconds

    # every train wall ends in a forced 1-element transfer of the score
    # (obs.sync): block_until_ready alone does not reliably synchronize
    global_timer.reset()
    with obs.wall("profile/warmup") as wt:
        wb = lgb.train(dict(params), ds, num_boost_round=BLOCK)
        obs.sync(wb.inner.train_score.score)
    t_warmup = wt.seconds
    warm_t = dict(global_timer.times)

    global_timer.reset()
    with obs.wall("profile/train") as wt:
        bst = lgb.train(dict(params), ds, num_boost_round=ITERS)
        obs.sync(bst.inner.train_score.score)
    t_train = wt.seconds
    train_t = dict(global_timer.times)

    # pure device time of one cached block: re-dispatch through the booster
    # machinery and block on the result
    global_timer.reset()
    with obs.wall("profile/train_warm_block") as wt:
        bst2 = lgb.train(dict(params), ds, num_boost_round=BLOCK)
        obs.sync(bst2.inner.train_score.score)
    t_train1 = wt.seconds
    one_t = dict(global_timer.times)

    with obs.wall("profile/eval_train") as wt:
        (_, _, auc, _), = bst.eval_train()
    t_eval = wt.seconds

    def fmt(d):
        return {k: round(v, 3) for k, v in sorted(d.items())}

    print("== profile_wall N=%d iters=%d block=%d ==" % (N, ITERS, BLOCK))
    print("import: %.2fs  datagen: %.2fs  construct: %.2fs" %
          (t_import, t_datagen, t_construct))
    print("warmup(%d it): %.2fs  %s" % (BLOCK, t_warmup, fmt(warm_t)))
    print("train(%d it): %.2fs  %s" % (ITERS, t_train, fmt(train_t)))
    print("train(%d it, warm): %.2fs  %s" % (BLOCK, t_train1, fmt(one_t)))
    print("eval_train: %.2fs auc=%.4f" % (t_eval, auc))
    acc = sum(train_t.values())
    print("timed-train accounted: %.2fs / %.2fs (%.0f%%)" %
          (acc, t_train, 100 * acc / max(t_train, 1e-9)))


if __name__ == "__main__":
    main()
