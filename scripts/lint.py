"""graftlint CLI: JAX-invariant static analysis over the repo.

Usage:
    python scripts/lint.py                      # lint default paths, human output
    python scripts/lint.py --json               # machine-readable findings
    python scripts/lint.py --update-baseline    # freeze current findings
    python scripts/lint.py --no-baseline        # show ALL findings
    python scripts/lint.py --list-rules         # rule table
    python scripts/lint.py lightgbm_tpu/ops     # restrict paths

Exit status: 0 when every finding is baselined or suppressed, 1 otherwise.
Pure stdlib — no jax import; a full-repo run stays well under the tier-1
~5 s budget (tests/test_lint.py enforces it).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu import lint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=list(lint.DEFAULT_PATHS),
                    help="files/dirs to lint (default: %s)"
                         % " ".join(lint.DEFAULT_PATHS))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object with findings + summary")
    ap.add_argument("--baseline", default=os.path.join(REPO,
                                                       lint.BASELINE_NAME),
                    help="baseline file (default: repo lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(lint.all_rules().items()):
            print("%-22s %s" % (rid, rule.description))
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    result = lint.run(REPO, args.paths, rules=rules)

    if args.update_baseline:
        lint.save_baseline(args.baseline,
                           lint.baseline_from_findings(result.findings))
        print("baseline updated: %s (%d findings frozen)"
              % (os.path.relpath(args.baseline, REPO), len(result.findings)))
        return 0

    if args.no_baseline:
        new, old = list(result.findings), []
    else:
        baseline = lint.load_baseline(args.baseline)
        new, old = lint.split_new_findings(result.findings, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in old],
            "suppressed": [vars(f) for f in result.suppressed],
            "files": len(result.project.files),
            "ok": not new,
        }))
    else:
        for f in new:
            print(f.render())
        print("graftlint: %d file(s), %d new finding(s), %d baselined, "
              "%d suppressed" % (len(result.project.files), len(new),
                                 len(old), len(result.suppressed)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
