"""graftlint CLI: JAX-invariant static analysis over the repo.

Usage:
    python scripts/lint.py                      # lint default paths, human output
    python scripts/lint.py --json               # machine-readable findings
    python scripts/lint.py --update-baseline    # freeze current findings
    python scripts/lint.py --no-baseline        # show ALL findings
    python scripts/lint.py --list-rules         # rule table
    python scripts/lint.py --changed            # only files dirty vs HEAD
    python scripts/lint.py lightgbm_tpu/ops     # restrict paths

Exit status: 0 when every finding is baselined or suppressed AND no
baseline entry went stale, 1 on new findings or baseline drift (a frozen
entry whose source line no longer exists — fix the baseline, it must
shrink monotonically), 2 on usage errors (unknown/empty --rules,
--changed without git, --update-baseline with --changed). Pure stdlib —
no jax import; a full-repo run stays well under the tier-1 ~5 s budget
(tests/test_lint.py enforces it).
"""
import argparse
import importlib.machinery
import importlib.util
import json
import os
import subprocess
import sys

_SRC = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# the linted tree defaults to this repo; tests point LGBTPU_LINT_ROOT at
# a fixture tree to drive the full CLI (baseline drift, exit codes)
# hermetically while the lint package still imports from here
REPO = os.environ.get("LGBTPU_LINT_ROOT", _SRC)
sys.path.insert(0, _SRC)

# lightgbm_tpu.lint is pure stdlib, but importing it through the real
# parent package would execute lightgbm_tpu/__init__.py — which pulls in
# jax and burns ~1.5s of the <5s budget before a single file is linted.
# Register a namespace-only parent so the subpackage loads alone.
if "lightgbm_tpu" not in sys.modules:
    _spec = importlib.machinery.ModuleSpec("lightgbm_tpu", None,
                                           is_package=True)
    _spec.submodule_search_locations = [os.path.join(_SRC, "lightgbm_tpu")]
    sys.modules["lightgbm_tpu"] = importlib.util.module_from_spec(_spec)

from lightgbm_tpu import lint  # noqa: E402


def _changed_paths(base_paths) -> list:
    """Paths (relative to REPO) of .py files differing from HEAD —
    modified, staged or untracked — restricted to the requested lint
    paths. The fast pre-commit mode: project-wide rules then reason over
    just the dirty subset."""
    cmds = (["git", "diff", "--name-only", "HEAD", "--"],
            ["git", "ls-files", "--others", "--exclude-standard"])
    names = []
    for cmd in cmds:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
        if proc.returncode != 0:
            print("graftlint: --changed needs a git checkout (%s)"
                  % (proc.stderr.strip() or "git failed"), file=sys.stderr)
            raise SystemExit(2)
        names.extend(proc.stdout.splitlines())
    roots = tuple(p.rstrip("/") for p in base_paths)
    out = []
    for n in sorted(set(names)):
        if not n.endswith(".py"):
            continue
        if any(n == r or n.startswith(r + "/") for r in roots) \
                and os.path.exists(os.path.join(REPO, n)):
            out.append(n)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=list(lint.DEFAULT_PATHS),
                    help="files/dirs to lint (default: %s)"
                         % " ".join(lint.DEFAULT_PATHS))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object with findings + summary")
    ap.add_argument("--baseline", default=os.path.join(REPO,
                                                       lint.BASELINE_NAME),
                    help="baseline file (default: repo lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(pruning stale entries) and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py files differing from HEAD "
                         "(within the requested paths)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(lint.all_rules().items()):
            print("%-22s %s" % (rid, rule.description))
        return 0

    rules = None
    if args.rules is not None:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        if not rules:
            print("graftlint: --rules needs at least one rule id "
                  "(see --list-rules)", file=sys.stderr)
            return 2
        unknown = sorted(set(rules) - set(lint.all_rules()))
        if unknown:
            print("graftlint: unknown rule(s): %s (see --list-rules)"
                  % ", ".join(unknown), file=sys.stderr)
            return 2

    if args.update_baseline and args.changed:
        print("graftlint: --update-baseline needs a full run — a "
              "--changed subset would drop every entry outside it",
              file=sys.stderr)
        return 2

    paths = args.paths
    if args.changed:
        paths = _changed_paths(paths)
        if not paths:
            # nothing to lint, but frozen entries can still have gone
            # stale (a fix committed without shrinking the baseline)
            stale = lint.stale_baseline_entries(
                REPO, lint.load_baseline(args.baseline))
            for e in stale:
                print("graftlint: stale baseline entry %s [%s] %r"
                      % (e.get("path"), e.get("rule"), e.get("text")))
            if stale:
                print("graftlint: %d stale baseline entr%s — run "
                      "scripts/lint.py --update-baseline"
                      % (len(stale), "y" if len(stale) == 1 else "ies"))
                return 1
            print("graftlint: no changed files under the requested paths")
            return 0

    result = lint.run(REPO, paths, rules=rules)

    if args.update_baseline:
        old_baseline = lint.load_baseline(args.baseline)
        new_baseline = lint.baseline_from_findings(result.findings)
        kept = {(e["path"], e["rule"], e["text"])
                for e in new_baseline["findings"]}
        pruned = sum(1 for e in old_baseline.get("findings", [])
                     if (e.get("path"), e.get("rule"), e.get("text"))
                     not in kept)
        lint.save_baseline(args.baseline, new_baseline)
        print("baseline updated: %s (%d findings frozen, %d stale "
              "entr%s pruned)"
              % (os.path.relpath(args.baseline, REPO),
                 len(result.findings), pruned,
                 "y" if pruned == 1 else "ies"))
        return 0

    stale = []
    if args.no_baseline:
        new, old = list(result.findings), []
    else:
        baseline = lint.load_baseline(args.baseline)
        new, old = lint.split_new_findings(result.findings, baseline)
        stale = lint.stale_baseline_entries(REPO, baseline)

    if args.as_json:
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": [vars(f) for f in old],
            "suppressed": [vars(f) for f in result.suppressed],
            "stale_baseline": stale,
            "files": len(result.project.files),
            "ok": not new and not stale,
        }))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print("graftlint: stale baseline entry %s [%s] %r"
                  % (e.get("path"), e.get("rule"), e.get("text")))
        print("graftlint: %d file(s), %d new finding(s), %d baselined, "
              "%d suppressed, %d stale baseline"
              % (len(result.project.files), len(new), len(old),
                 len(result.suppressed), len(stale)))
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
