"""Interleaved A/B: resident slim payload vs planes vs rows work layouts.

Measures the per-split hot paths the resident state changes — partition
(route pre-pass + slim payload move vs full packed-row move) and segment
histogram (gather through the permuted ridx plane vs unit-stride payload
read) — plus a full-train wall per layout, under measurement discipline v2
(PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (alternating src/dst plane parity and the mutated work buffer), so the
  tunnel cannot deduplicate bit-identical re-executions;
- every wall ends in a forced 1-element device_get (`np.asarray(..)[:1]`);
- per-op time = (t_K - t_1) / (K - 1), best-of-R, which cancels the
  dispatch + sync overhead shared by both chain lengths.

Also prints the deterministic bytes-moved-per-row traffic table (the
CPU-measurable half of the acceptance bar: the resident partition must
move >= 2x less data per split than planes at F=28).

On a TPU backend the pallas kernels run natively; elsewhere they are
skipped unless LGBTPU_PALLAS_INTERPRET=1 (interpreter numbers are
correctness-only — never quote them as perf).

Usage: python scripts/resident_bisect.py [n_rows] [num_feat] [train_rows]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import (
    hist16_segment, hist16_segment_planes, hist16_segment_resident)

CH = 1024        # partition chunk (pallas optimum, PERF.md round 5)
HCH = 4096       # histogram chunk
REPS = 5
K = 4


def build_inputs(n, f, num_bin=256, seed=0):
    rng = np.random.RandomState(seed)
    guard = max(P.guard_rows(CH), CH + 2 * P.PLANE_ALIGN)
    npad = ((n + 2 * guard + 127) // 128) * 128
    bins_pad = np.zeros((npad, f), np.uint8)
    bins_pad[guard:guard + n] = rng.randint(0, num_bin, (n, f))
    ghc_pad = np.zeros((npad, 3), np.float32)
    ghc_pad[guard:guard + n] = rng.randn(n, 3).astype(np.float32)
    ghc_pad[guard:guard + n, 2] = 1.0
    bins = jnp.asarray(bins_pad[guard:guard + n])
    ghc = jnp.asarray(ghc_pad[guard:guard + n])

    w_r = P.pack_rows(jnp.asarray(bins_pad), jnp.asarray(ghc_pad))
    if w_r.shape[1] % 128:           # rows pallas kernel wants 128-mult width
        w_r = jnp.pad(w_r, ((0, 0), (0, 128 - w_r.shape[1] % 128)))
    work_r = jnp.stack([w_r, jnp.zeros_like(w_r)])

    w_p = P.pack_planes(jnp.asarray(bins_pad), jnp.asarray(ghc_pad))
    wpad = (-w_p.shape[0]) % 32
    if wpad:
        w_p = jnp.pad(w_p, ((0, wpad), (0, 0)))
    work_p = jnp.stack([w_p, jnp.zeros_like(w_p)])

    res = P.resident_bin_planes(bins, guard, npad)
    _, w_rs = P.work_spec(f, False, "pallas", CH, HCH, layout="resident")
    work_s = jnp.zeros((2, w_rs, npad), jnp.uint8)
    work_s, _ = P.pack_resident_fold_root(
        work_s, bins, ghc, guard, num_bins=num_bin, exact=True, chunk=HCH)

    table = jnp.asarray(rng.rand(num_bin) < 0.5)
    return work_r, work_p, work_s, res, table, guard


def part_make(fn, work, guard, n, table, ch):
    def make(k):
        @jax.jit
        def f(work):
            def body(carry, _):
                w, c = carry
                w2, _lt = fn(w, c % 2, jnp.int32(guard), jnp.int32(n),
                             jnp.int32(3), table, ch=ch)
                return (w2, 1 - c), None
            (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None,
                                     length=k)
            return w.reshape(-1)[:1]
        return lambda: f(work)
    return make


def part_make_resident(fn, work, res, guard, n, table, ch):
    """Resident partition = route-plane gather pre-pass + the SAME planes
    partition (XLA or fused Mosaic) routing on plane 0 (feat=0)."""
    def make(k):
        @jax.jit
        def f(work, res):
            def body(carry, _):
                w, c = carry
                w = P.write_route_plane(w, res, c % 2, jnp.int32(guard),
                                        jnp.int32(n), jnp.int32(3), ch=ch)
                w2, _lt = fn(w, c % 2, jnp.int32(guard), jnp.int32(n),
                             jnp.int32(0), table, ch=ch)
                return (w2, 1 - c), None
            (w, _), _ = jax.lax.scan(body, (work, jnp.int32(0)), None,
                                     length=k)
            return w.reshape(-1)[:1]
        return lambda: f(work, res)
    return make


def hist_make(fn, work, guard, n, f_real, shift, *extra):
    def make(k):
        @jax.jit
        def f(work, *extra):
            def body(carry, _):
                s, acc = carry
                h = fn(work, *extra, jnp.int32(0),
                       jnp.int32(guard + s % 64), jnp.int32(n - 64),
                       num_bins=256, num_feat=f_real, chunk=HCH)
                return (s + shift, acc + h[0, 0, 0]), None
            (_, acc), _ = jax.lax.scan(body, (jnp.int32(0), jnp.float32(0)),
                                       None, length=k)
            return acc.reshape(1)
        return lambda: f(work, *extra)
    return make


def train_wall(layout, resident, n, f, iters=10, seed=3):
    """Wall of one warm `lgb.train` at the given layout (high-level API:
    the fused trainer, sampling, split scan and transfers all ride in)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "tpu_iter_block": 5,
              "tpu_work_layout": layout,
              "tpu_resident_state": "on" if resident else "off"}
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=5)        # warmup/compile
    def run():
        with obs.wall("bisect/train_" + ("resident" if resident else layout),
                      record=False) as w:
            bst = lgb.train(dict(params), ds, num_boost_round=iters)
            obs.sync(bst.inner.train_score.score)   # trusted wall end
        return w.seconds
    return run


def main(n, f, train_n):
    backend = jax.default_backend()
    pallas_ok = backend in ("tpu", "axon") or P._INTERPRET
    work_r, work_p, work_s, res, table, guard = build_inputs(n, f)
    print(f"backend={backend} n={n} F={f} row_w={work_r.shape[2]} "
          f"planes_w={work_p.shape[1]} resident_w={work_s.shape[1]} "
          f"guard={guard} (pallas {'on' if pallas_ok else 'SKIPPED — no TPU'})")

    # ---- deterministic traffic table (bytes per parent row per split) ----
    print("\ntraffic (bytes moved per parent row per split, XLA widths):")
    w_rows = f + P.GH_BYTES
    w_planes = f + P.GH_BYTES
    w_res = P.RST_WIDTH
    rows = [("rows", 2 * w_rows, w_rows),
            ("planes", 2 * w_planes, w_planes),
            ("resident", 2 * w_res + P.RST_GH_OFF + 1, w_res + f)]
    for name, part_b, hist_b in rows:
        print(f"  {name:10s} partition={part_b:4d} B/row   "
              f"hist={hist_b:4d} B/row")
    cut = rows[1][1] / rows[2][1]
    print(f"  resident partition cut vs planes: {cut:.2f}x "
          f"({'MEETS' if cut >= 2.0 else 'BELOW'} the >=2x acceptance bar)")

    # ---- kernel-level interleaved A/B ----
    pairs = [
        ("part/rows/xla",
         part_make(P.partition_segment, work_r, guard, n, table, CH)),
        ("part/planes/xla",
         part_make(P.partition_segment_planes, work_p, guard, n, table, CH)),
        ("part/resident/xla",
         part_make_resident(P.partition_segment_planes, work_s, res, guard,
                            n, table, CH)),
    ]
    if pallas_ok:
        pairs += [
            ("part/planes/pallas",
             part_make(P.partition_segment_planes_fused, work_p, guard, n,
                       table, CH)),
            ("part/resident/pallas",
             part_make_resident(P.partition_segment_planes_fused, work_s,
                                res, guard, n, table, CH)),
        ]
    pairs += [
        ("hist/rows/xla",
         hist_make(hist16_segment, work_r, guard, n, f, 1)),
        ("hist/planes/xla",
         hist_make(hist16_segment_planes, work_p, guard, n, f, 1)),
        ("hist/resident/xla",
         hist_make(hist16_segment_resident, work_s, guard, n, f, 1, res)),
    ]
    res_t = obs.ab_interleaved(pairs, reps=REPS, k=K)
    print()
    for name, per in res_t.items():
        print(f"{name:24s} {per * 1e3:8.3f} ms  ({n / per / 1e6:7.1f} M rows/s)")
    for stem in ("part", "hist"):
        base = res_t.get(f"{stem}/planes/xla")
        if base:
            for k, v in res_t.items():
                if k.startswith(stem):
                    print(f"  {k:22s} {base / v:5.2f}x vs {stem} planes/xla")

    # ---- full-train wall, interleaved across layouts ----
    if train_n > 0:
        runs = [("train/rows", train_wall("rows", False, train_n, f)),
                ("train/planes", train_wall("planes", False, train_n, f)),
                ("train/resident", train_wall("planes", True, train_n, f))]
        best = {name: np.inf for name, _ in runs}
        for _ in range(3):
            for name, run in runs:           # A, B, C, A, B, C per rep
                best[name] = min(best[name], run())
        print()
        for name, w in best.items():
            print(f"{name:24s} {w:8.3f} s  (10 iters, n={train_n})")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    train_n = int(sys.argv[3]) if len(sys.argv) > 3 else 300_000
    main(n, f, train_n)
