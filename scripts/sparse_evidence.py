"""Sparse-storage decision evidence (VERDICT r4 item 9).

The reference keeps a CSR sparse bin store (src/io/sparse_bin.hpp) for
datasets like Allstate (13M x 4228 one-hot). The TPU-native design instead
relies on EFB: mutually-exclusive one-hot blocks bundle into dense
columns, so the dense u8 store covers the same workloads. This script
MEASURES that claim on an Allstate-like synthetic: ~4228 one-hot columns
from ~130 categorical variables, plus a few dense numericals.

Usage: python scripts/sparse_evidence.py [rows]   (default 500_000)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from lightgbm_tpu import obs

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000


def main():
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    n_cat = 130
    card = 32          # ~130 * 32 + 68 dense-ish = ~4228 raw columns
    n_dense = 68
    with obs.wall("sparse_evidence/gen", record=False) as w_gen:
        # one-hot blocks: exactly one hot column per categorical variable
        cats = rng.randint(0, card, size=(N, n_cat))
        cols = []
        X = np.zeros((N, n_cat * card + n_dense), dtype=np.float64)
        for j in range(n_cat):
            X[np.arange(N), j * card + cats[:, j]] = 1.0
        X[:, n_cat * card:] = rng.randn(N, n_dense)
        y = (cats[:, 0] + X[:, -1] * 3 + rng.randn(N) > card / 2).astype(
            np.float64)
    print("gen %.1fs: raw shape %s (%.2f GB dense f64, %.4f density of "
          "the one-hot block)" % (w_gen.seconds, X.shape,
                                  X.nbytes / 1e9, 1.0 / card), flush=True)
    with obs.wall("sparse_evidence/construct", record=False) as w_cons:
        ds = lgb.Dataset(X, label=y)
        ds.construct()
    t_cons = w_cons.seconds
    inner = ds.construct()
    G = inner.num_groups
    print("construct %.1fs: %d raw features -> %d EFB bundles "
          "(binned matrix %d x %d u8 = %.3f GB; the reference's CSR "
          "store exists to avoid a %d-wide dense store — EFB removes the "
          "need at the source)"
          % (t_cons, X.shape[1], G, N, G, N * G / 1e9, X.shape[1]),
        flush=True)
    with obs.wall("sparse_evidence/train", record=False) as w_tr:
        bst = lgb.train({"objective": "binary", "num_leaves": 63,
                         "verbosity": -1, "metric": ["auc"],
                         "tpu_iter_block": 5}, ds, num_boost_round=10)
        (_, _, auc, _), = bst.eval_train()
    print("train 10 iters %.1fs auc=%.4f" % (w_tr.seconds, auc),
          flush=True)


if __name__ == "__main__":
    main()
