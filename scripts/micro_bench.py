"""Microbenchmarks for the round-2 histogram/partition design (TPU).

Run on the real chip: python scripts/micro_bench.py
Measures the primitives the partitioned learner is built from:
  - full-N one-hot histogram (f32 HIGHEST vs bf16 hi/lo einsum)
  - pallas histogram kernel
  - row gather (index list -> (C, F) slab)
  - compaction (mask -> packed index list) via cumsum+scatter
  - argsort-based compaction for comparison
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    obs.sync(r)
    # trusted wall per PERF.md discipline: the timed block ends with a
    # forced 1-element transfer of the last result
    with obs.wall("micro_bench", record=False) as w:
        for _ in range(iters):
            r = fn(*args)
        obs.sync(r)
    return w.seconds / iters


def main():
    print("devices:", jax.devices())
    N, F, B = 2_000_000, 28, 256
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, B, size=(N, F)), jnp.uint8)
    ghc = jnp.asarray(rng.randn(N, 3), jnp.float32)
    row_leaf = jnp.asarray(rng.randint(0, 8, size=(N,)), jnp.int32)

    from lightgbm_tpu.ops.histogram import build_histogram_jit

    for mxu_bf16 in (False, True):
        for chunk in (2048, 8192, 32768):
            t = timeit(build_histogram_jit, bins, ghc, B, chunk, mxu_bf16)
            flops = N * F * B * 3 * 2
            print(f"einsum bf16={mxu_bf16} chunk={chunk}: {t*1e3:.1f} ms "
                  f"({N/t/1e6:.1f} M rows/s, {flops/t/1e12:.2f} eff TFLOP/s)")

    # gather a compacted chunk
    idx = jnp.asarray(rng.randint(0, N, size=(16384,)), jnp.int32)

    @jax.jit
    def gather(idx):
        return bins[idx], ghc[idx]

    t = timeit(gather, idx)
    print(f"gather 16384 rows: {t*1e6:.0f} us ({16384/t/1e6:.1f} M rows/s)")

    # compaction: mask -> packed indices
    mask = row_leaf == 3

    @jax.jit
    def compact_scatter(mask):
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        cnt = pos[-1] + 1
        buf = jnp.zeros((N,), jnp.int32)
        buf = buf.at[jnp.where(mask, pos, N)].set(
            jnp.arange(N, dtype=jnp.int32), mode="drop")
        return buf, cnt

    t = timeit(compact_scatter, mask)
    print(f"compact scatter N={N}: {t*1e3:.2f} ms")

    @jax.jit
    def compact_sort(mask):
        return jnp.argsort(~mask, stable=True)

    t = timeit(compact_sort, mask)
    print(f"compact argsort N={N}: {t*1e3:.2f} ms")

    @jax.jit
    def just_cumsum(mask):
        return jnp.cumsum(mask.astype(jnp.int32))

    t = timeit(just_cumsum, mask)
    print(f"cumsum N={N}: {t*1e3:.2f} ms")

    # segment-local chunked partition cost model: gather + small ops per chunk
    @jax.jit
    def route(idx):
        col = bins[idx, 5].astype(jnp.int32)
        return col < 128

    t = timeit(route, idx)
    print(f"route 16384 rows: {t*1e6:.0f} us")


if __name__ == "__main__":
    main()
