"""Measure trace/lower/compile cost of the fused training block at bench shape."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import lightgbm_tpu as lgb
from bench import make_higgs_like

N = int(os.environ.get("PROF_N", 2_000_000))
X, y = make_higgs_like(N)
params = {
    "objective": "binary", "num_leaves": 255, "max_bin": 255,
    "learning_rate": 0.1, "verbosity": -1, "tpu_iter_block": 20,
}

with obs.wall("trace_cost/construct", record=False) as w:
    ds = lgb.Dataset(X, label=y)
    ds.construct()
print(f"dataset construct: {w.seconds:.1f}s")

for rep in range(3):
    with obs.wall("trace_cost/train", record=False) as w:
        bst = lgb.train(dict(params), ds, num_boost_round=20)
    print(f"train#{rep} 20 iters: {w.seconds:.1f}s")
