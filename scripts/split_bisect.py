"""Interleaved A/B: one-kernel split vs the three-launch chain.

Measures what ISSUE 13 fused — per split, the three-launch oracle
(fused partition pallas_call, smaller-child segment histogram, vmapped
find_best_split scan) against ONE pallas_call running all three phases
back-to-back in VMEM (ops/partition.py one_kernel_split_planes) — under
measurement discipline v2 (PERF.md):

- single process, A and B INTERLEAVED trial-by-trial (the device clock
  drifts between runs; only same-process comparisons are trusted);
- each trial is a K-chained scan whose body threads a CHANGING carry
  (alternating src/dst plane parity and the mutated work buffer), so the
  tunnel cannot deduplicate bit-identical re-executions;
- every wall ends in a forced 1-element device_get;
- per-split time = (t_K - t_1) / (K - 1), best-of-R, which cancels the
  dispatch + sync overhead shared by both chain lengths.

This is the validation gate for the tpu_split_kernel auto knob: auto
stays "off" until a v5e session runs this script, confirms the Mosaic
lowering of the in-kernel scan tail and a wall win, and flips the knob
(or lets the run ledger carry the measured answer forward).

On a TPU backend the kernels run natively; elsewhere they are skipped
unless LGBTPU_PALLAS_INTERPRET=1 (interpreter numbers are
correctness-only — never quote them as perf).

Usage: python scripts/split_bisect.py [n_rows] [num_feat] [train_rows]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as P
from lightgbm_tpu.ops.histogram import hist16_segment_planes
from lightgbm_tpu.ops.split import FeatureMeta, SplitHyper, find_best_split

CH = 1024        # partition chunk (pallas optimum, PERF.md round 5)
HCH = 2048       # histogram chunk (one-kernel DMA window)
NUM_BIN = 64
REPS = 5
K = 4


def build_inputs(n, f, seed=0):
    rng = np.random.RandomState(seed)
    guard = max(P.guard_rows(CH), CH + 2 * P.PLANE_ALIGN,
                HCH + 2 * P.PLANE_ALIGN)
    npad = max(P.planes_npad(n, guard, "pallas"),
               ((n + 2 * guard + 127) // 128) * 128)
    bins = jnp.asarray(rng.randint(0, NUM_BIN, (n, f)).astype(np.uint8))
    ghc = rng.randn(n, 3).astype(np.float32)
    ghc[:, 2] = 1.0
    ghc = jnp.asarray(ghc)
    _, w_pl = P.work_spec(f, False, "pallas", CH, HCH, layout="planes")
    work = jnp.zeros((2, w_pl, npad), jnp.uint8)
    work, root = P.pack_planes_fold_root(work, bins, ghc, guard,
                                         num_bins=NUM_BIN, exact=True,
                                         chunk=HCH)
    meta = FeatureMeta(
        num_bins=jnp.full((f,), NUM_BIN, jnp.int32),
        movable_missing=jnp.zeros((f,), bool),
        missing_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int8),
        penalty=jnp.ones((f,), jnp.float32),
        cegb_coupled=jnp.zeros((f,), jnp.float32))
    hp = SplitHyper(min_data_in_leaf=2.0)
    fmask = jnp.ones((f,), bool)
    info0 = find_best_split(root, jnp.sum(ghc, axis=0), meta, fmask, hp)
    return work, root, guard, meta, hp, fmask, info0


def make_three_launch(work, root, guard, meta, hp, fmask, info0, n, f):
    """B: the retained oracle — partition launch, smaller-child histogram
    launch, split-scan launch (exactly what the learner's off path runs)."""
    ls = info0.left_sum[2] <= info0.right_sum[2]
    sums2 = jnp.stack([info0.left_sum, info0.right_sum])
    aux = (jnp.zeros((2,), jnp.float32),
           jnp.full((2,), -jnp.inf, jnp.float32),
           jnp.full((2,), jnp.inf, jnp.float32))
    scan = jax.vmap(lambda hg, tg, po, lo, up: find_best_split(
        hg, tg, meta, fmask, hp, parent_output=po, leaf_lower=lo,
        leaf_upper=up, node_depth=jnp.int32(1)))

    def make(k):
        @jax.jit
        def run(work):
            def body(carry, _):
                w, c, acc = carry
                w, lt = P.partition_segment_planes_fused(
                    w, c % 2, jnp.int32(guard), jnp.int32(n),
                    info0.feature, info0.go_left, ch=CH)
                ss = jnp.where(ls, jnp.int32(guard), jnp.int32(guard) + lt)
                sc = jnp.where(ls, lt, jnp.int32(n) - lt)
                hs = hist16_segment_planes(w, 1 - c % 2, ss, sc,
                                           num_bins=NUM_BIN, num_feat=f,
                                           chunk=HCH)
                hg = root - hs
                hl = jnp.where(ls, hs, hg)
                hr = jnp.where(ls, hg, hs)
                infos = scan(jnp.stack([hl, hr]), sums2, *aux)
                return (w, 1 - c, acc + infos.gain[0]), None
            (w, _, acc), _ = jax.lax.scan(
                body, (work, jnp.int32(0), jnp.float32(0)), None, length=k)
            return w.reshape(-1)[:1], acc
        return lambda: run(work)
    return make


def make_one_kernel(work, root, guard, meta, hp, fmask, info0, n, f):
    """A: the fused op — one pallas_call per split."""
    ls = info0.left_sum[2] <= info0.right_sum[2]
    sums2 = jnp.stack([info0.left_sum, info0.right_sum])
    aux = (jnp.zeros((2,), jnp.float32),
           jnp.full((2,), -jnp.inf, jnp.float32),
           jnp.full((2,), jnp.inf, jnp.float32))

    def make(k):
        @jax.jit
        def run(work):
            def body(carry, _):
                w, c, acc = carry
                w, _lt, _hl, _hr, infos = P.one_kernel_split_planes(
                    w, c % 2, jnp.int32(guard), jnp.int32(n), info0.feature,
                    info0.go_left, ls, jnp.int32(1), root, meta, fmask,
                    sums2, *aux, hp, num_bins=NUM_BIN, num_feat=f,
                    ch=CH, hist_chunk=HCH)
                return (w, 1 - c, acc + infos.gain[0]), None
            (w, _, acc), _ = jax.lax.scan(
                body, (work, jnp.int32(0), jnp.float32(0)), None, length=k)
            return w.reshape(-1)[:1], acc
        return lambda: run(work)
    return make


def train_wall(split_kernel, n, f, iters=10, seed=3):
    """Wall of one warm `lgb.train` with the knob forced on/off (the fused
    trainer, sampling and transfers all ride in)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X @ rng.randn(f) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": NUM_BIN,
              "verbosity": -1, "tpu_iter_block": 5,
              "tpu_work_layout": "planes", "tpu_partition_kernel": "pallas",
              "tpu_split_kernel": split_kernel}
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=5)        # warmup/compile
    def run():
        with obs.wall("bisect/train_split_" + split_kernel,
                      record=False) as w:
            bst = lgb.train(dict(params), ds, num_boost_round=iters)
            obs.sync(bst.inner.train_score.score)   # trusted wall end
        return w.seconds
    return run


def main(n, f, train_n):
    backend = jax.default_backend()
    pallas_ok = backend in ("tpu", "axon") or P._INTERPRET
    if not pallas_ok:
        print(f"backend={backend}: no Mosaic and LGBTPU_PALLAS_INTERPRET "
              "unset — nothing to bisect (both arms need the pallas "
              "partition stream). Exiting.")
        return
    work, root, guard, meta, hp, fmask, info0 = build_inputs(n, f)
    print(f"backend={backend} n={n} F={f} planes_w={work.shape[1]} "
          f"guard={guard} bins={NUM_BIN}"
          + (" [INTERPRET — correctness only, not perf]"
             if P._INTERPRET and backend not in ("tpu", "axon") else ""))

    args = (work, root, guard, meta, hp, fmask, info0, n, f)
    res = obs.ab_interleaved(
        [("split/three_launch", make_three_launch(*args)),
         ("split/one_kernel", make_one_kernel(*args))],
        reps=REPS, k=K)
    print()
    for name, per in res.items():
        print(f"{name:24s} {per * 1e3:8.3f} ms/split  "
              f"({n / per / 1e6:7.1f} M rows/s)")
    base = res.get("split/three_launch")
    one = res.get("split/one_kernel")
    if base and one:
        verdict = ("WIN — flip tpu_split_kernel auto to on"
                   if base / one > 1.02 else "NO WIN — keep auto=off")
        print(f"\none-kernel speedup: {base / one:.2f}x ({verdict})")

    if train_n > 0:
        runs = [("train/off", train_wall("off", train_n, f)),
                ("train/on", train_wall("on", train_n, f))]
        best = {name: np.inf for name, _ in runs}
        for _ in range(3):
            for name, run in runs:           # A, B, A, B per rep
                best[name] = min(best[name], run())
        print()
        for name, w in best.items():
            print(f"{name:24s} {w:8.3f} s  (10 iters, n={train_n})")


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    train_n = int(sys.argv[3]) if len(sys.argv) > 3 else 300_000
    main(n, f, train_n)
