"""Query / compare / regression-gate CLI over the JSONL run ledger.

Usage:
    python scripts/ledger.py list   [--path L] [--kind train] [-n 10]
    python scripts/ledger.py show   [--path L] [--index -1]
    python scripts/ledger.py compare --metrics extra.train_s,... \
                                    [--index-a -2] [--index-b -1]
    python scripts/ledger.py train  [--path L] [--rows N] [--features F]
    python scripts/ledger.py gate   [--path L] --metric extra.train_s \
                                    [--tolerance 0.25]

``train`` runs a small deterministic CI workload with ``obs_ledger`` on
(appending one entry with its trusted train wall under ``extra.train_s``)
and ``gate`` fails (exit 1) when the newest entry matching the same
(machine, shape, config) key regressed more than ``--tolerance`` vs the
previous one — the ``scripts/check.sh --ledger`` pair, same shape as the
``--slo`` gate. ``gate`` passes when fewer than two matching entries
exist, so the first run on a fresh machine cannot fail CI.

Query modes never import jax-heavy modules until needed; a ledger copied
off a TPU host can be inspected anywhere.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATH = os.path.join(REPO, "lgbtpu_ledger.jsonl")

# the CI workload: fixed shape + params so every `train` run lands on the
# same ledger match key (rows/features overridable for bigger machines)
CI_ROWS, CI_FEATURES = 2000, 10
CI_PARAMS = {
    "objective": "binary", "num_leaves": 31, "verbosity": -1,
    "tpu_iter_block": 5, "seed": 7,
}
CI_ROUNDS = 10


def _entries(path, kind=None):
    from lightgbm_tpu import obs_ledger
    out = list(obs_ledger.read_entries(path))
    if kind:
        out = [e for e in out if e.get("kind") == kind]
    return out


def _pick(entries, index):
    try:
        return entries[index]
    except IndexError:
        sys.exit("ledger: no entry at index %d (have %d)"
                 % (index, len(entries)))


def _fmt_ts(ts):
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(float(ts)))


def cmd_list(args):
    entries = _entries(args.path, args.kind)
    if args.n:
        entries = entries[-args.n:]
    if not entries:
        print("ledger: no entries at %s" % args.path)
        return 0
    print("%-4s %-19s %-6s %-8s %10s %5s  %-16s %-5s %s"
          % ("idx", "ts", "kind", "backend", "rows", "feat",
             "config_fp", "knobs", "fleet"))
    base = len(_entries(args.path, args.kind))
    for i, e in enumerate(entries):
        ds, m = e.get("dataset", {}), e.get("machine", {})
        # serve entries from fleet runs carry role/holder/lease epoch so
        # trainer vs standby vs replica processes tell apart at a glance
        fl = (e.get("extra") or {}).get("fleet") or {}
        ftxt = "%s@%s %s" % (fl.get("role", "?"),
                             fl.get("lease_epoch", 0),
                             fl.get("holder", "")) if fl else ""
        print("%-4d %-19s %-6s %-8s %10s %5s  %-16s %-5d %s"
              % (i - len(entries) + base, _fmt_ts(e.get("ts", 0)),
                 e.get("kind", "?"), m.get("backend", "?"),
                 ds.get("rows", "?"), ds.get("features", "?"),
                 e.get("config_fp", "?"),
                 len(e.get("resolved_knobs", {})), ftxt))
    return 0


def cmd_show(args):
    entries = _entries(args.path, args.kind)
    print(json.dumps(_pick(entries, args.index), indent=2, sort_keys=True))
    return 0


def cmd_compare(args):
    from lightgbm_tpu import obs_ledger
    entries = _entries(args.path, args.kind)
    a, b = _pick(entries, args.index_a), _pick(entries, args.index_b)
    metrics = [m for m in args.metrics.split(",") if m]
    print("%-40s %14s %14s %8s" % ("metric", "a", "b", "b/a"))
    for m, va, vb in obs_ledger.compare(a, b, metrics):
        ratio = ("%8.3f" % (vb / va)) if va and vb is not None else "     n/a"
        print("%-40s %14s %14s %s"
              % (m, "n/a" if va is None else "%.6g" % va,
                 "n/a" if vb is None else "%.6g" % vb, ratio))
    return 0


def _ci_config(path, rows, features):
    from lightgbm_tpu.config import Config
    params = dict(CI_PARAMS, obs_ledger=True, obs_ledger_path=path)
    return Config.from_params(params), params


def cmd_train(args):
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs

    rows, features = args.rows, args.features
    rng = np.random.RandomState(7)
    X = rng.rand(rows, features).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.75).astype(np.float32)
    _, params = _ci_config(args.path, rows, features)
    obs.telemetry.reset()
    ds = lgb.Dataset(X, label=y)
    booster = None
    with obs.wall("ledger_ci_train") as w:
        booster = lgb.train(params, ds, num_boost_round=CI_ROUNDS)
        obs.sync(booster.inner.train_score.score)   # trusted wall: end in a transfer
    # the engine already appended the run entry; stamp the trusted train
    # wall into a second, richer entry the gate compares on
    from lightgbm_tpu import obs_ledger
    entry = obs_ledger.record_run(
        booster.inner.config, "bench", rows, features,
        extra={"train_s": round(w.seconds, 6), "rounds": CI_ROUNDS})
    print(json.dumps({"train_s": round(w.seconds, 6),
                      "rows": rows, "features": features,
                      "entry_written": entry is not None,
                      "path": args.path}))
    return 0 if entry is not None else 1


def cmd_gate(args):
    from lightgbm_tpu import obs_ledger
    cfg, _ = _ci_config(args.path, args.rows, args.features)
    ok, msg = obs_ledger.gate(args.path, cfg, args.rows, args.features,
                              args.metric, args.tolerance, kind="bench")
    print(("PASS " if ok else "FAIL ") + msg)
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--path", default=DEFAULT_PATH)
        p.add_argument("--kind", default=None,
                       help="filter: train | bench | serve")

    p = sub.add_parser("list", help="table of entries")
    common(p)
    p.add_argument("-n", type=int, default=20)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("show", help="dump one entry as JSON")
    common(p)
    p.add_argument("--index", type=int, default=-1)
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("compare", help="metric diff between two entries")
    common(p)
    p.add_argument("--metrics",
                   default="extra.train_s,"
                           "telemetry.timers.fused/device_wait,"
                           "telemetry.timers.fused/logs_transfer,"
                           "telemetry.jit_compiles.total")
    p.add_argument("--index-a", type=int, default=-2)
    p.add_argument("--index-b", type=int, default=-1)
    p.set_defaults(fn=cmd_compare)

    p = sub.add_parser("train", help="run the CI workload, append entry")
    common(p)
    p.add_argument("--rows", type=int, default=CI_ROWS)
    p.add_argument("--features", type=int, default=CI_FEATURES)
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("gate", help="fail on regression vs previous entry")
    common(p)
    p.add_argument("--rows", type=int, default=CI_ROWS)
    p.add_argument("--features", type=int, default=CI_FEATURES)
    p.add_argument("--metric", default="extra.train_s")
    p.add_argument("--tolerance", type=float, default=0.25,
                   help="fractional regression allowed (0.25 = +25%%)")
    p.set_defaults(fn=cmd_gate)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
