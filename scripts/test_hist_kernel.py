"""Correctness + speed: hist_pallas_segment vs the XLA einsum path."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu import obs

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from lightgbm_tpu.ops.histogram import hist16_segment, hist_pallas_segment
from lightgbm_tpu.ops.partition import pack_rows, work_spec

B = 256


def build(n, F, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B, size=(n, F)).astype(np.uint8)
    ghc = rng.randn(n, 3).astype(np.float32)
    ghc[:, 2] = 1.0
    guard, width = work_spec(F, False, "pallas", 1024, 4096)
    pad = ((guard, guard), (0, 0))
    w0 = pack_rows(jnp.pad(jnp.asarray(bins), pad),
                   jnp.pad(jnp.asarray(ghc), pad))
    w0 = jnp.pad(w0, ((0, 0), (0, width - w0.shape[1])))
    return jnp.stack([w0, jnp.zeros_like(w0)]), guard


def check(n, F, start_off, cnt, chunk=4096):
    work, guard = build(n, F)
    args = (work, jnp.int32(0), jnp.int32(guard + start_off), jnp.int32(cnt))
    kw = dict(num_bins=B, num_feat=F, exact=True, chunk=chunk)
    ref = np.asarray(jax.jit(lambda *a: hist16_segment(*a, **kw))(*args))
    out = np.asarray(jax.jit(lambda *a: hist_pallas_segment(*a, **kw))(*args))
    same = np.array_equal(ref, out)
    close = np.allclose(ref, out, rtol=1e-6, atol=1e-4)
    print("n=%d F=%d off=%d cnt=%d: bitexact=%s close=%s maxdiff=%.3g"
          % (n, F, start_off, cnt, same, close, np.abs(ref - out).max()))
    assert close


def speed(n, F, chunk=4096, reps=60):
    work, guard = build(n, F)
    kw = dict(num_bins=B, num_feat=F, exact=True, chunk=chunk)

    def mk(fn):
        @jax.jit
        def chain(work):
            def body(i, acc):
                h = fn(work, jnp.int32(0), jnp.int32(guard), jnp.int32(n),
                       **kw)
                return acc + h[0, 0, 0]
            return jax.lax.fori_loop(0, reps, body, jnp.float32(0))
        obs.sync(chain(work))
        best = 1e9
        for _ in range(3):
            with obs.wall("test_hist_kernel/chain", record=False) as w:
                obs.sync(chain(work))
            best = min(best, w.seconds)
        return best / reps

    t_x = mk(hist16_segment)
    t_p = mk(hist_pallas_segment)
    print("n=%d F=%d chunk=%d: xla %.2f ms (%.2f ns/row)  pallas %.2f ms "
          "(%.2f ns/row)" % (n, F, chunk, t_x * 1e3, t_x / n * 1e9,
                             t_p * 1e3, t_p / n * 1e9))


if __name__ == "__main__":
    check(20000, 28, 0, 20000)
    check(20000, 28, 37, 12345)
    check(20000, 28, 1, 1)
    speed(2_000_000, 28)
    speed(2_000_000, 28, chunk=8192)
