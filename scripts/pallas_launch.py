"""Measure bare pallas_call launch overhead: trivial kernel chained 254x."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from lightgbm_tpu import obs
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

REPS = 254


def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...] + 1.0


@jax.jit
def chain(x):
    def body(i, x):
        return pl.pallas_call(
            kern,
            name="launch_probe",
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
    return jax.lax.fori_loop(0, REPS, body, x)


x = jnp.zeros((256, 128), jnp.float32)
obs.sync(chain(x))
best = 1e9
for _ in range(3):
    with obs.wall("pallas_launch/trivial", record=False) as w:
        obs.sync(chain(x))
    best = min(best, w.seconds)
print("trivial pallas: %.1f us/call" % (best / REPS * 1e6))


# same but as a plain XLA op for comparison
@jax.jit
def chain_xla(x):
    def body(i, x):
        return x + 1.0
    return jax.lax.fori_loop(0, REPS, body, x)


obs.sync(chain_xla(x))
best = 1e9
for _ in range(3):
    with obs.wall("pallas_launch/xla", record=False) as w:
        obs.sync(chain_xla(x))
    best = min(best, w.seconds)
print("plain XLA add: %.1f us/call" % (best / REPS * 1e6))

# trivial kernel with HBM work buffer + aliasing + scalar prefetch,
# mimicking the partition call signature
N = 1 << 21
work = jnp.zeros((2, N, 128), jnp.uint8)


def kern2(sref, w_in, w_ref, o_ref, sem):
    i = sref[0]
    cp = pltpu.make_async_copy(w_in.at[0, pl.ds(0, 256), :],
                               o_ref.at[...], sem)
    cp.start()
    cp.wait()


@jax.jit
def chain2(work):
    def body(i, carry):
        work, acc = carry
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.HBM)],
            out_specs=[pl.BlockSpec(memory_space=pltpu.HBM),
                       pl.BlockSpec(memory_space=pltpu.VMEM)],
            scratch_shapes=[pltpu.SemaphoreType.DMA],
        )
        w2, o = pl.pallas_call(
            kern2,
            name="launch_probe_grid",
            grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct(work.shape, work.dtype),
                       jax.ShapeDtypeStruct((256, 128), jnp.uint8)],
            input_output_aliases={1: 0},
        )(jnp.stack([i.astype(jnp.int32)]), work)
        return w2, acc + jnp.sum(o.astype(jnp.int32))
    return jax.lax.fori_loop(0, REPS, body, (work, jnp.int32(0)))


obs.sync(chain2(work))
best = 1e9
for _ in range(3):
    with obs.wall("pallas_launch/hbm_alias", record=False) as w:
        obs.sync(chain2(work))
    best = min(best, w.seconds)
print("HBM+alias pallas: %.1f us/call" % (best / REPS * 1e6))
